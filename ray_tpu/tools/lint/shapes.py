"""Abstract shape/dtype/sharding interpretation for `ray-tpu lint`.

The RTL8xx family (rules_shapes.py) reasons about *array geometry* where
the earlier families reason about names: does the buffer a caller feeds a
jitted program actually have the shape the traced body requires? Does a
donated buffer alias any output, or does donation silently degrade to a
copy? Does the mesh axis size divide the dim a PartitionSpec shards?

This module is the engine under those rules: a small abstract
interpreter that evaluates Python functions over an abstract array
domain —

  * **dims** are polynomials over named symbols with integer
    coefficients (`Dim`): `128`, `B`, `nb*bs`, `k+1` are all exact
    values; arithmetic (`+ - * //`) stays symbolic, and inexact
    division introduces a fresh *quotient symbol* (`(s//bs)`) so that
    two occurrences of the same expression remain provably equal;
  * **finite sets** (`ElementOf`) model values drawn from a
    statically-resolved bucket table — the join of the loop variable in
    `for b in (8, 16, 32): ...` — which is what lets RTL805 compare a
    fed width against the table that warmed the program;
  * **arrays** (`AbstractArray`) carry a shape tuple (dims may be TOP),
    a dtype (numpy-style promotion over the common names), and an
    optional sharding;
  * **TOP** is the explicit "don't know" for anything unmodeled. Every
    propagation rule and every check degrades to TOP/silence rather
    than guessing — unknowns can never fire a finding, so the RTL8xx
    rules are false-positive-free *by construction* (a finding always
    comes with two statically-proven, contradictory facts).

Two facts are only ever *provably* different when their difference is a
nonzero constant (`bucket` vs `bucket + 8`, `5` vs `3`), never merely
"not syntactically equal" — `B` vs `C` stays silent because nothing
rules out B == C at runtime.

The interpreter walks real statements (assignments with unpacking,
branches joined, loops run to a two-pass fixpoint, calls into
project-resolvable functions inlined to a small depth) and models the
common jnp/np/lax surface: constructors, reshape/transpose, matmul /
einsum, concatenate/stack, slicing and `.at[...].set`, dynamic_slice,
reductions, astype, where/broadcasting, plus `jax.jit` (via the RTL5xx
binding parser, so donate/static argnums ride along), `shard_map`,
`Mesh`/`PartitionSpec`/`NamedSharding` and `device_put` /
`with_sharding_constraint`. Geometry contradictions (reshape size,
matmul contraction, broadcast, concatenate) land in an error sink the
rules attribute to the jitted call site under scrutiny.
"""

from __future__ import annotations

import ast
import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.tools.lint.core import (
    ModuleInfo,
    _resolve_function,
    call_kwargs,
    resolve_name_binding,
)

# ---------------------------------------------------------------------------
# the abstract domain
# ---------------------------------------------------------------------------


class _Top:
    """The explicit unknown. Any operation touching TOP yields TOP, and
    no check ever fires on it."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "TOP"


TOP = _Top()


class Dim:
    """A dimension as a polynomial over named symbols: `{monomial:
    coeff}` where a monomial is a sorted tuple of symbol names and `()`
    is the constant term. Exact arithmetic keeps expressions like
    `nb*bs` and `k+1` comparable; inexact ops mint composite symbols
    (`(a//b)`) so equal expressions stay equal."""

    __slots__ = ("terms",)

    def __init__(self, terms: Dict[Tuple[str, ...], int]):
        self.terms = {m: c for m, c in terms.items() if c != 0}

    @staticmethod
    def const(value: int) -> "Dim":
        return Dim({(): int(value)})

    @staticmethod
    def symbol(name: str) -> "Dim":
        return Dim({(name,): 1})

    @property
    def is_const(self) -> bool:
        return all(m == () for m in self.terms)

    @property
    def const_value(self) -> Optional[int]:
        return self.terms.get((), 0) if self.is_const else None

    def key(self) -> tuple:
        return tuple(sorted(self.terms.items()))

    def __eq__(self, other) -> bool:
        return isinstance(other, Dim) and self.terms == other.terms

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        if not self.terms:
            return "0"
        parts = []
        for m, c in sorted(self.terms.items()):
            name = "*".join(m) if m else ""
            if name:
                parts.append(name if c == 1 else f"{c}*{name}")
            else:
                parts.append(str(c))
        return "+".join(parts).replace("+-", "-")

    # -- arithmetic ---------------------------------------------------------

    def add(self, other: "Dim") -> "Dim":
        out = dict(self.terms)
        for m, c in other.terms.items():
            out[m] = out.get(m, 0) + c
        return Dim(out)

    def neg(self) -> "Dim":
        return Dim({m: -c for m, c in self.terms.items()})

    def sub(self, other: "Dim") -> "Dim":
        return self.add(other.neg())

    def mul(self, other: "Dim") -> "Dim":
        out: Dict[Tuple[str, ...], int] = {}
        for m1, c1 in self.terms.items():
            for m2, c2 in other.terms.items():
                m = tuple(sorted(m1 + m2))
                out[m] = out.get(m, 0) + c1 * c2
        if len(out) > 16:  # runaway products are not worth tracking
            return Dim.symbol(f"({self!r}*{other!r})")
        return Dim(out)

    def floordiv(self, other: "Dim"):
        """Exact division when provable, else a canonical quotient
        symbol — floor semantics must not be simplified away."""
        d = other.const_value
        if d is not None:
            if d == 0:
                return TOP
            if all(c % d == 0 for c in self.terms.values()):
                return Dim({m: c // d for m, c in self.terms.items()})
        if self == other:
            return Dim.const(1)
        return Dim.symbol(f"({self!r}//{other!r})")

    def mod(self, other: "Dim"):
        d = other.const_value
        if d is not None and d != 0 and all(
            c % d == 0 for c in self.terms.values()
        ):
            return Dim.const(0)
        if self == other:
            return Dim.const(0)
        return Dim.symbol(f"({self!r}%{other!r})")

    # -- decision procedures ------------------------------------------------

    def provably_ne(self, other: "Dim") -> bool:
        """True only when the difference is a nonzero constant — the one
        case where inequality holds for EVERY symbol assignment."""
        diff = self.sub(other)
        return diff.is_const and diff.const_value != 0

    def divisible_by(self, k: int) -> Optional[bool]:
        """True/False when provable, None when unknown: all coefficients
        divisible -> yes; only the constant term indivisible -> no."""
        if k <= 0:
            return None
        non_const_ok = all(
            c % k == 0 for m, c in self.terms.items() if m != ()
        )
        if not non_const_ok:
            return None
        return self.terms.get((), 0) % k == 0


class ElementOf:
    """An integer drawn from a statically-known finite set — e.g. the
    loop variable ranging over a bucket table."""

    __slots__ = ("values",)
    MAX = 64

    def __init__(self, values):
        self.values = frozenset(int(v) for v in values)

    def __eq__(self, other):
        return isinstance(other, ElementOf) and self.values == other.values

    def __hash__(self):
        return hash(self.values)

    def __repr__(self):
        return f"ElementOf({sorted(self.values)})"

    def map(self, fn):
        out = {fn(v) for v in self.values}
        if len(out) > self.MAX:
            return TOP
        if len(out) == 1:
            return Dim.const(next(iter(out)))
        return ElementOf(out)


class Opaque:
    """An unknown value with an identity: the attribute/subscript path
    it was read from (`self.cfg.block_size`, `tokens.shape[1]`). Two
    reads of the same path inside one root evaluation denote the same
    value, which is what makes symbolic shape equality provable."""

    __slots__ = ("path",)

    def __init__(self, path: str):
        self.path = path

    def __repr__(self):
        return f"Opaque({self.path})"


@dataclasses.dataclass
class AbstractArray:
    """shape: tuple of Dim/ElementOf/TOP, or TOP for unknown rank."""

    shape: object  # tuple | TOP
    dtype: object  # str | TOP
    sharding: object = None  # ShardingVal | None

    @property
    def rank(self) -> Optional[int]:
        return len(self.shape) if isinstance(self.shape, tuple) else None

    def with_(self, shape=None, dtype=None):
        return AbstractArray(
            shape=self.shape if shape is None else shape,
            dtype=self.dtype if dtype is None else dtype,
            sharding=self.sharding,
        )


@dataclasses.dataclass
class AbstractMesh:
    names: object  # tuple[str, ...] | TOP
    sizes: object  # tuple[int, ...] | TOP

    def axis_size(self, name: str) -> Optional[int]:
        if not isinstance(self.names, tuple) or not isinstance(
            self.sizes, tuple
        ):
            return None
        try:
            return self.sizes[self.names.index(name)]
        except ValueError:
            return None


@dataclasses.dataclass
class SpecVal:
    """PartitionSpec: one entry per dim — a tuple of axis names (an
    entry like `("dp", "fsdp")` shards one dim over both), None for
    replicated, TOP for unresolvable."""

    entries: Tuple[object, ...]


@dataclasses.dataclass
class ShardingVal:
    mesh: object  # AbstractMesh | TOP
    spec: object  # SpecVal | TOP


@dataclasses.dataclass
class JitProgram:
    """A value bound to `jax.jit(fn, ...)`. `binding` is the RTL5xx
    JitBinding (donate/static argnums in the caller's self-less view);
    `module` is the module DEFINING the wrapped function."""

    module: ModuleInfo
    binding: object  # rules_donation.JitBinding


@dataclasses.dataclass
class ShardMapProgram:
    module: ModuleInfo
    fn_value: object  # FuncVal | TOP
    mesh: object  # AbstractMesh | TOP
    in_specs: object  # tuple of SpecVal/TOP | TOP
    call: ast.Call


@dataclasses.dataclass
class FuncVal:
    module: ModuleInfo
    fn: ast.AST  # FunctionDef | Lambda


@dataclasses.dataclass
class PartialVal:
    func: object
    args: tuple
    keywords: dict


@dataclasses.dataclass
class ModuleRef:
    module: ModuleInfo


@dataclasses.dataclass
class ExternalRef:
    """A dotted name rooted outside the project (jnp/np/lax/...)."""

    dotted: str


@dataclasses.dataclass
class BoundMethod:
    recv: object
    name: str


@dataclasses.dataclass
class AtView:
    arr: AbstractArray


@dataclasses.dataclass
class AtIndexed:
    arr: AbstractArray
    index_shape: object  # abstract shape of the selected region, or TOP


@dataclasses.dataclass
class ListRepeat:
    """`[x] * n` — a host list whose length is an abstract dim."""

    elem: object
    length: object  # Dim | ElementOf | TOP


@dataclasses.dataclass
class GeometryError:
    node: ast.AST
    message: str


# ---------------------------------------------------------------------------
# dtypes
# ---------------------------------------------------------------------------

DTYPE_NAMES = {
    "bool", "int8", "uint8", "int16", "int32", "int64",
    "float16", "bfloat16", "float32", "float64",
}
FLOAT_DTYPES = {"float16", "bfloat16", "float32", "float64"}
_PROMOTE_ORDER = [
    "bool", "int8", "uint8", "int16", "int32", "int64",
    "float16", "bfloat16", "float32", "float64",
]


def dtype_of(value) -> object:
    """Map an abstract value used in dtype position to a dtype name."""
    if isinstance(value, str) and value in DTYPE_NAMES:
        return value
    if isinstance(value, ExternalRef):
        last = value.dotted.rsplit(".", 1)[-1]
        if last in DTYPE_NAMES:
            return last
        if last == "float":
            return "float64"
        if last == "int":
            return "int64"
    return TOP


def promote(a, b):
    if a is TOP or b is TOP:
        return TOP
    if a == b:
        return a
    if a not in _PROMOTE_ORDER or b not in _PROMOTE_ORDER:
        return TOP
    hi = max(a, b, key=_PROMOTE_ORDER.index)
    lo = min(a, b, key=_PROMOTE_ORDER.index)
    # bf16/f16 are unordered siblings: their join is f32.
    if {hi, lo} == {"bfloat16", "float16"}:
        return "float32"
    return hi


# ---------------------------------------------------------------------------
# dim coercion / joins
# ---------------------------------------------------------------------------


def as_dim(value):
    """Coerce an abstract value into a shape-dim: Dim/ElementOf pass
    through, ints become constants, Opaques become symbols, everything
    else is TOP."""
    if isinstance(value, (Dim, ElementOf)):
        return value
    if isinstance(value, bool):
        return TOP
    if isinstance(value, int):
        return Dim.const(value)
    if isinstance(value, Opaque):
        return Dim.symbol(value.path)
    return TOP


def as_shape(value) -> object:
    """Coerce a value used as a shape argument: a tuple/list of
    dim-ables, or a single *explicitly scalar* dim for 1-d
    constructors. An Opaque here stays TOP — it could be a tuple at
    runtime, and guessing rank 1 would manufacture false mismatches."""
    if isinstance(value, (tuple, list)):
        return tuple(as_dim(v) for v in value)
    if isinstance(value, (int, Dim, ElementOf)) and not isinstance(
        value, bool
    ):
        d = as_dim(value)
        return TOP if d is TOP else (d,)
    return TOP


def dims_equal(a, b) -> Optional[bool]:
    """True / False when provable, None when unknown."""
    if a is TOP or b is TOP:
        return None
    if isinstance(a, Dim) and isinstance(b, Dim):
        if a == b:
            return True
        if a.provably_ne(b):
            return False
        return None
    if isinstance(a, ElementOf) and isinstance(b, Dim):
        c = b.const_value
        if c is not None and c not in a.values:
            return False
        return None
    if isinstance(a, Dim) and isinstance(b, ElementOf):
        return dims_equal(b, a)
    if isinstance(a, ElementOf) and isinstance(b, ElementOf):
        if not (a.values & b.values):
            return False
        return None
    return None


def join_dim(a, b):
    if a is TOP or b is TOP:
        return TOP
    if a == b:
        return a
    av = a.values if isinstance(a, ElementOf) else (
        {a.const_value} if isinstance(a, Dim) and a.is_const else None
    )
    bv = b.values if isinstance(b, ElementOf) else (
        {b.const_value} if isinstance(b, Dim) and b.is_const else None
    )
    if av is not None and bv is not None:
        merged = av | bv
        if len(merged) <= ElementOf.MAX:
            return ElementOf(merged)
    return TOP


def join(a, b):
    """Join of two abstract values (if/loop merge). Conservative: equal
    values survive, joinable families join, everything else is TOP."""
    if a is b:
        return a
    if a is TOP or b is TOP:
        return TOP
    if isinstance(a, bool) and isinstance(b, bool):
        return a if a == b else TOP
    if isinstance(a, (int, Dim, ElementOf)) and isinstance(
        b, (int, Dim, ElementOf)
    ):
        return join_dim(as_dim(a), as_dim(b))
    if isinstance(a, AbstractArray) and isinstance(b, AbstractArray):
        if isinstance(a.shape, tuple) and isinstance(b.shape, tuple) and (
            len(a.shape) == len(b.shape)
        ):
            shape = tuple(
                join_dim(x, y) for x, y in zip(a.shape, b.shape)
            )
        else:
            shape = TOP
        return AbstractArray(
            shape=shape,
            dtype=a.dtype if a.dtype == b.dtype else TOP,
        )
    if isinstance(a, tuple) and isinstance(b, tuple) and len(a) == len(b):
        return tuple(join(x, y) for x, y in zip(a, b))
    if a == b:
        return a
    return TOP


def shape_fully_known(shape) -> bool:
    return isinstance(shape, tuple) and all(
        isinstance(d, Dim) for d in shape
    )


def total_size(shape):
    out = Dim.const(1)
    for d in shape:
        if not isinstance(d, Dim):
            return None
        out = out.mul(d)
    return out


def flatten_leaves(value) -> Optional[List[object]]:
    """Pytree leaves of a return value; None when the structure itself
    is unknown (a TOP anywhere that could HIDE an array)."""
    if isinstance(value, (tuple, list)):
        out: List[object] = []
        for v in value:
            sub = flatten_leaves(v)
            if sub is None:
                return None
            out.extend(sub)
        return out
    if value is TOP or isinstance(value, Opaque):
        return None
    return [value]


# ---------------------------------------------------------------------------
# broadcasting
# ---------------------------------------------------------------------------


def broadcast_dims(a, b, sink: Optional[List[str]] = None):
    """Broadcast two dims; a provable conflict (both known, neither
    provably-1-compatible) appends a message to `sink`."""
    if a is TOP or b is TOP:
        return TOP
    one = Dim.const(1)
    if isinstance(a, Dim) and a == one:
        return b
    if isinstance(b, Dim) and b == one:
        return a
    eq = dims_equal(a, b)
    if eq:
        return a
    if eq is False:
        # Only a provable conflict when neither side can still be 1.
        a_not_one = dims_equal(a, one) is False
        b_not_one = dims_equal(b, one) is False
        if a_not_one and b_not_one and sink is not None:
            sink.append(f"cannot broadcast dim {a!r} with {b!r}")
        return TOP
    return TOP


def broadcast_shapes(sa, sb, sink: Optional[List[str]] = None):
    if not isinstance(sa, tuple) or not isinstance(sb, tuple):
        return TOP
    out = []
    la, lb = len(sa), len(sb)
    for i in range(max(la, lb)):
        da = sa[la - 1 - i] if i < la else Dim.const(1)
        db = sb[lb - 1 - i] if i < lb else Dim.const(1)
        out.append(broadcast_dims(da, db, sink))
    return tuple(reversed(out))


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------

_EXTERNAL_ROOTS = (
    "jax", "numpy", "jax.numpy", "jax.lax", "jax.nn", "functools",
    "jax.sharding", "jax.experimental", "jax.experimental.mesh_utils",
)

_ELEMENTWISE_UNARY = {
    "exp", "log", "sqrt", "tanh", "sin", "cos", "abs", "negative",
    "relu", "gelu", "sigmoid", "softmax", "log_softmax", "square",
    "rsqrt", "sign", "floor", "ceil", "stop_gradient", "copy",
}
_ELEMENTWISE_BINARY = {
    "add", "subtract", "multiply", "divide", "true_divide", "maximum",
    "minimum", "power", "mod", "equal", "not_equal", "greater", "less",
    "greater_equal", "less_equal", "logical_and", "logical_or",
}
_REDUCTIONS = {
    "sum", "mean", "max", "min", "prod", "all", "any", "argmax",
    "argmin", "var", "std",
}


class _Return(Exception):
    pass


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Budget(Exception):
    pass


class Frame:
    __slots__ = ("module", "env", "attrs", "returns")

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.env: Dict[str, object] = {}
        # (base path, attr) -> value, for `self.x = ...` style stores.
        self.attrs: Dict[Tuple[str, str], object] = {}
        self.returns: List[object] = []


class Interp:
    """One root evaluation. Hooks:

    jit_resolver(module, call) -> Optional[(def_module, JitBinding)] —
        maps a call node to the jit binding it dispatches to (self-attr
        and base-chain resolution lives in rules_shapes).
    on_jit_call(call, module, def_module, binding, args, kwargs) ->
        abstract result (or TOP). `args is None` means the call site's
        arguments could not be modeled (e.g. an opaque *splat).
    on_sharding_apply(call, module, array, sharding) — device_put /
        with_sharding_constraint sites.
    on_shard_call(call, module, program, args) — invocation of a
        shard_map-wrapped callable.
    on_assign(module, node, name, value) — every name/self-attr bind
        (RTL804 pairing harvest).
    """

    MAX_DEPTH = 5

    def __init__(
        self,
        project,
        jit_resolver: Optional[Callable] = None,
        on_jit_call: Optional[Callable] = None,
        on_sharding_apply: Optional[Callable] = None,
        on_shard_call: Optional[Callable] = None,
        on_assign: Optional[Callable] = None,
        budget: int = 20000,
    ):
        self.project = project
        self.jit_resolver = jit_resolver
        self.on_jit_call = on_jit_call
        self.on_sharding_apply = on_sharding_apply
        self.on_shard_call = on_shard_call
        self.on_assign = on_assign
        self.errors: List[GeometryError] = []
        self._budget = budget
        self._depth = 0
        self._global_memo: Dict[Tuple[int, str], object] = {}
        self._opaque_counter = itertools.count()
        # self-token path -> (module, ClassDef): lets `self.X` reads in
        # a method seed from the class's __init__ assignments. Tokens
        # are per-class so a root in class A calling class B's bound
        # jit program never sees A's attributes as B's.
        self._self_classes: Dict[str, Tuple[ModuleInfo, ast.AST]] = {}
        self._class_attrs: Dict[int, object] = {}

    # -- error sink ---------------------------------------------------------

    def geometry_error(self, node: ast.AST, message: str) -> None:
        self.errors.append(GeometryError(node, message))

    def _flush_sink(self, node: ast.AST, sink: List[str]) -> None:
        for msg in sink:
            self.geometry_error(node, msg)

    # -- function evaluation ------------------------------------------------

    def eval_root(
        self, module: ModuleInfo, fn: ast.AST
    ) -> Tuple[object, Frame]:
        """Evaluate `fn` as an analysis root: every parameter seeded as
        an Opaque symbol. Returns (joined return value, final frame) —
        the frame's env/attrs hold the JOINED post-body bindings, which
        is what geometry pairing rules must look at (a value assigned
        in only one branch joins to TOP and stays silent)."""
        frame = Frame(module)
        args = fn.args if not isinstance(fn, ast.Module) else None
        if args is not None:
            for p in (*args.posonlyargs, *args.args, *args.kwonlyargs):
                frame.env[p.arg] = Opaque(p.arg)
            if args.vararg is not None:
                frame.env[args.vararg.arg] = TOP
            if args.kwarg is not None:
                frame.env[args.kwarg.arg] = TOP
            params = [p.arg for p in (*args.posonlyargs, *args.args)]
            if params and params[0] in ("self", "cls"):
                frame.env[params[0]] = self.self_token(module, fn)
        self._depth += 1
        try:
            try:
                self.exec_body(frame, fn.body)
            except (_Return, _Break, _Continue):
                pass
        except _Budget:
            pass
        finally:
            self._depth -= 1
        out: object = TOP
        if frame.returns:
            out = frame.returns[0]
            for r in frame.returns[1:]:
                out = join(out, r)
        return out, frame

    def eval_function(
        self,
        module: ModuleInfo,
        fn: ast.AST,
        args: Sequence[object],
        kwargs: Optional[Dict[str, object]] = None,
        self_value: object = None,
    ) -> object:
        """Evaluate a FunctionDef/Lambda body with abstract arguments;
        returns the join of its returns (TOP when nothing resolves)."""
        if self._depth >= self.MAX_DEPTH:
            return TOP
        kwargs = kwargs or {}
        frame = Frame(module)
        params = [
            p.arg for p in (*fn.args.posonlyargs, *fn.args.args)
        ]
        pos = list(args)
        if self_value is not None and params and params[0] in (
            "self", "cls"
        ):
            frame.env[params[0]] = self_value
            params = params[1:]
        if len(pos) > len(params) and fn.args.vararg is None:
            return TOP  # arity mismatch: do not guess a binding
        for name, value in zip(params, pos):
            frame.env[name] = value
        for name in params[len(pos):]:
            if name in kwargs:
                frame.env[name] = kwargs[name]
            else:
                frame.env[name] = Opaque(name)
        for p in fn.args.kwonlyargs:
            frame.env[p.arg] = kwargs.get(p.arg, Opaque(p.arg))
        if fn.args.vararg is not None:
            frame.env[fn.args.vararg.arg] = tuple(pos[len(params):])
        if fn.args.kwarg is not None:
            frame.env[fn.args.kwarg.arg] = TOP
        self._depth += 1
        try:
            if isinstance(fn, ast.Lambda):
                return self.eval_expr(frame, fn.body)
            try:
                self.exec_body(frame, fn.body)
            except _Return:
                pass
            except (_Break, _Continue):
                pass
        except _Budget:
            return TOP
        finally:
            self._depth -= 1
        if not frame.returns:
            return TOP
        out = frame.returns[0]
        for r in frame.returns[1:]:
            out = join(out, r)
        return out

    def fresh_opaque(self, label: str) -> Opaque:
        return Opaque(f"{label}#{next(self._opaque_counter)}")

    # -- class-level self-attribute seeding ---------------------------------

    def self_token(self, module: ModuleInfo, fn: ast.AST) -> Opaque:
        """The `self` value for a method of a statically-known class:
        an Opaque whose path is registered so attribute reads can seed
        from the class's __init__. Falls back to a plain Opaque for
        functions with no enclosing class."""
        cls = module.parent(fn)
        while cls is not None and not isinstance(cls, ast.ClassDef):
            cls = module.parent(cls)
        if cls is None:
            return Opaque("self")
        token = f"self@{module.relpath}:{cls.name}"
        self._self_classes[token] = (module, cls)
        return Opaque(token)

    @staticmethod
    def _plain_method(
        module: ModuleInfo, cls: ast.AST, attr: str
    ) -> Optional[ast.AST]:
        """An undecorated instance method named `attr` on `cls` (a
        decorated one — staticmethod, cached, remote — is opaque)."""
        for member in cls.body:
            if (
                isinstance(member, ast.FunctionDef)
                and member.name == attr
                and not member.decorator_list
            ):
                return member
        return None

    @staticmethod
    def _property_getter(
        module: ModuleInfo, cls: ast.AST, attr: str
    ) -> Optional[ast.AST]:
        """The @property getter for `cls.attr`, when one exists — a
        `self.X` read through a property is as seedable as an __init__
        assignment (the runner's `self._pools` tuple)."""
        for member in cls.body:
            if not isinstance(member, ast.FunctionDef):
                continue
            if member.name != attr:
                continue
            for dec in member.decorator_list:
                if isinstance(dec, ast.Name) and dec.id == "property":
                    return member
        return None

    def class_self_attrs(self, module: ModuleInfo, cls: ast.AST) -> Dict:
        """attr -> abstract value assigned to `self.attr` in the
        class's __init__ (base classes merged first, subclass wins),
        evaluated once per class with __init__'s parameters as Opaque
        symbols. A cycle returns {} while in progress."""
        state = self._class_attrs.get(id(cls), "miss")
        if state == "busy":
            return {}
        if state != "miss":
            return state
        self._class_attrs[id(cls)] = "busy"
        out: Dict[str, object] = {}
        if self.project is not None:
            for base in cls.bases:
                sym = self.project.resolve_expr(module, base)
                if sym is not None and isinstance(
                    sym.node, ast.ClassDef
                ):
                    out.update(
                        self.class_self_attrs(sym.module, sym.node)
                    )
        init = next(
            (
                m for m in cls.body
                if isinstance(m, ast.FunctionDef)
                and m.name == "__init__"
            ),
            None,
        )
        if init is not None and self._depth < self.MAX_DEPTH:
            token = f"self@{module.relpath}:{cls.name}"
            frame = Frame(module)
            for p in (*init.args.posonlyargs, *init.args.args,
                      *init.args.kwonlyargs):
                frame.env[p.arg] = Opaque(p.arg)
            params = [
                p.arg for p in (*init.args.posonlyargs, *init.args.args)
            ]
            if params:
                frame.env[params[0]] = Opaque(token)
            self._depth += 1
            try:
                try:
                    self.exec_body(frame, init.body)
                except (_Return, _Break, _Continue):
                    pass
            except _Budget:
                pass
            finally:
                self._depth -= 1
            for (base_path, attr), value in frame.attrs.items():
                if base_path == token:
                    out[attr] = value
        self._class_attrs[id(cls)] = out
        return out

    # -- statements ---------------------------------------------------------

    def exec_body(self, frame: Frame, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.exec_stmt(frame, stmt)

    def _tick(self) -> None:
        self._budget -= 1
        if self._budget <= 0:
            raise _Budget

    def exec_stmt(self, frame: Frame, stmt: ast.stmt) -> None:
        self._tick()
        if isinstance(stmt, ast.Return):
            value = (
                self.eval_expr(frame, stmt.value)
                if stmt.value is not None
                else None
            )
            frame.returns.append(value)
            raise _Return
        if isinstance(stmt, ast.Assign):
            value = self.eval_expr(frame, stmt.value)
            for target in stmt.targets:
                self.bind(frame, target, value, stmt)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.bind(
                    frame, stmt.target,
                    self.eval_expr(frame, stmt.value), stmt,
                )
            return
        if isinstance(stmt, ast.AugAssign):
            # x += y: evaluate as BinOp on the current binding.
            cur = self.eval_expr(frame, stmt.target)
            rhs = self.eval_expr(frame, stmt.value)
            value = self.binop(stmt, type(stmt.op), cur, rhs)
            self.bind(frame, stmt.target, value, stmt)
            return
        if isinstance(stmt, ast.Expr):
            self.eval_expr(frame, stmt.value)
            return
        if isinstance(stmt, ast.If):
            cond = self.eval_expr(frame, stmt.test)
            if cond is True:
                self.exec_body(frame, stmt.body)
                return
            if cond is False:
                self.exec_body(frame, stmt.orelse)
                return
            self._exec_branches(frame, [stmt.body, stmt.orelse])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._exec_for(frame, stmt)
            return
        if isinstance(stmt, ast.While):
            self._exec_loop_body(frame, stmt.body)
            self.exec_body(frame, stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                value = self.eval_expr(frame, item.context_expr)
                if item.optional_vars is not None:
                    self.bind(frame, item.optional_vars, value, stmt)
            self.exec_body(frame, stmt.body)
            return
        if isinstance(stmt, ast.Try):
            branches = [stmt.body]
            for handler in stmt.handlers:
                branches.append(handler.body)
            self._exec_branches(frame, branches)
            self.exec_body(frame, stmt.orelse)
            self.exec_body(frame, stmt.finalbody)
            return
        if isinstance(stmt, ast.Raise):
            raise _Return  # this path produces no value
        if isinstance(stmt, ast.Break):
            raise _Break
        if isinstance(stmt, ast.Continue):
            raise _Continue
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            frame.env[stmt.name] = FuncVal(frame.module, stmt)
            return
        if isinstance(stmt, (ast.Assert, ast.Pass, ast.Import,
                             ast.ImportFrom, ast.Global, ast.Nonlocal,
                             ast.Delete, ast.ClassDef)):
            return
        # Unknown statement kinds are skipped, not guessed at.
        return

    def _exec_branches(
        self, frame: Frame, bodies: Sequence[Sequence[ast.stmt]]
    ) -> None:
        """Execute alternative branches on env copies and join."""
        base_env = dict(frame.env)
        base_attrs = dict(frame.attrs)
        envs: List[Tuple[Dict, Dict]] = []
        raised = 0
        for body in bodies:
            frame.env = dict(base_env)
            frame.attrs = dict(base_attrs)
            try:
                self.exec_body(frame, body)
            except _Return:
                raised += 1
                continue
            except (_Break, _Continue):
                pass
            envs.append((frame.env, frame.attrs))
        if not envs:
            frame.env, frame.attrs = base_env, base_attrs
            if raised == len(bodies):
                raise _Return
            return
        env, attrs = envs[0]
        for e2, a2 in envs[1:]:
            env = self._join_maps(env, e2)
            attrs = self._join_maps(attrs, a2)
        frame.env, frame.attrs = env, attrs

    @staticmethod
    def _join_maps(a: Dict, b: Dict) -> Dict:
        out = {}
        for k in set(a) | set(b):
            if k in a and k in b:
                out[k] = join(a[k], b[k])
            else:
                out[k] = TOP
        return out

    def _exec_for(self, frame: Frame, stmt: ast.For) -> None:
        it = self.eval_expr(frame, stmt.iter)
        elem: object = TOP
        if isinstance(it, (tuple, list)) and 0 < len(it) <= 32:
            elem = it[0]
            for v in it[1:]:
                elem = join(elem, v)
        elif isinstance(it, ListRepeat):
            elem = it.elem
        self.bind(frame, stmt.target, elem, stmt)
        self._exec_loop_body(frame, stmt.body)
        self.exec_body(frame, stmt.orelse)

    def _exec_loop_body(
        self, frame: Frame, body: Sequence[ast.stmt]
    ) -> None:
        """Two-pass fixpoint: run the body, join with the pre-state, run
        again so loop-carried bindings (pool = pool.at[...].set(...))
        see their joined value."""
        for _ in range(2):
            pre_env = dict(frame.env)
            pre_attrs = dict(frame.attrs)
            try:
                self.exec_body(frame, body)
            except (_Break, _Continue):
                pass
            except _Return:
                # A returning path inside the loop: record and continue
                # with the pre-loop view joined in.
                pass
            frame.env = self._join_maps(pre_env, frame.env)
            frame.attrs = self._join_maps(pre_attrs, frame.attrs)

    # -- binding ------------------------------------------------------------

    def bind(
        self, frame: Frame, target: ast.AST, value, stmt: ast.stmt
    ) -> None:
        if isinstance(target, ast.Name):
            frame.env[target.id] = value
            if self.on_assign is not None:
                self.on_assign(frame.module, stmt, target.id, value)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(value, (tuple, list)) and len(value) == len(
                elts
            ) and not any(isinstance(e, ast.Starred) for e in elts):
                for el, v in zip(elts, value):
                    self.bind(frame, el, v, stmt)
            else:
                for el in elts:
                    if isinstance(el, ast.Starred):
                        self.bind(frame, el.value, TOP, stmt)
                    else:
                        self.bind(frame, el, TOP, stmt)
            return
        if isinstance(target, ast.Attribute):
            base = self.eval_expr(frame, target.value)
            if isinstance(base, Opaque):
                frame.attrs[(base.path, target.attr)] = value
                if self.on_assign is not None:
                    self.on_assign(
                        frame.module, stmt, target.attr, value
                    )
            return
        if isinstance(target, ast.Subscript):
            self.eval_expr(frame, target.value)
            return
        if isinstance(target, ast.Starred):
            self.bind(frame, target.value, TOP, stmt)

    # -- expressions --------------------------------------------------------

    def eval_expr(self, frame: Frame, node: ast.AST) -> object:
        self._tick()
        module = frame.module
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in frame.env:
                return frame.env[node.id]
            return self._resolve_global(module, node.id, node)
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(frame, node)
        if isinstance(node, ast.Call):
            return self._eval_call(frame, node)
        if isinstance(node, ast.Tuple):
            return self._eval_elts(frame, node.elts, tuple)
        if isinstance(node, ast.List):
            return self._eval_elts(frame, node.elts, list)
        if isinstance(node, ast.BinOp):
            left = self.eval_expr(frame, node.left)
            right = self.eval_expr(frame, node.right)
            return self.binop(node, type(node.op), left, right)
        if isinstance(node, ast.UnaryOp):
            v = self.eval_expr(frame, node.operand)
            if isinstance(node.op, ast.USub):
                if isinstance(v, int):
                    return -v
                if isinstance(v, Dim):
                    return v.neg()
            if isinstance(node.op, ast.Not) and isinstance(v, bool):
                return not v
            return TOP
        if isinstance(node, ast.Compare):
            return self._eval_compare(frame, node)
        if isinstance(node, ast.BoolOp):
            values = [self.eval_expr(frame, v) for v in node.values]
            if all(isinstance(v, bool) for v in values):
                if isinstance(node.op, ast.And):
                    return all(values)
                return any(values)
            return TOP
        if isinstance(node, ast.IfExp):
            cond = self.eval_expr(frame, node.test)
            if cond is True:
                return self.eval_expr(frame, node.body)
            if cond is False:
                return self.eval_expr(frame, node.orelse)
            return join(
                self.eval_expr(frame, node.body),
                self.eval_expr(frame, node.orelse),
            )
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(frame, node)
        if isinstance(node, ast.Lambda):
            return FuncVal(module, node)
        if isinstance(node, ast.Starred):
            return self.eval_expr(frame, node.value)
        if isinstance(node, ast.JoinedStr):
            return TOP
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            return TOP
        if isinstance(node, ast.Dict):
            for v in node.values:
                if v is not None:
                    self.eval_expr(frame, v)
            return TOP
        if isinstance(node, ast.NamedExpr):
            value = self.eval_expr(frame, node.value)
            self.bind(frame, node.target, value, node)
            return value
        if isinstance(node, ast.Await):
            return self.eval_expr(frame, node.value)
        if isinstance(node, ast.Slice):
            return TOP
        return TOP

    def _eval_elts(self, frame, elts, ctor):
        out = []
        for el in elts:
            if isinstance(el, ast.Starred):
                v = self.eval_expr(frame, el.value)
                if isinstance(v, (tuple, list)):
                    out.extend(v)
                else:
                    return TOP
            else:
                out.append(self.eval_expr(frame, el))
        return ctor(out)

    # -- names / attributes -------------------------------------------------

    def _resolve_global(
        self, module: ModuleInfo, name: str, at: ast.AST
    ) -> object:
        alias = module.aliases.get(name)
        if alias is not None:
            if self.project is not None:
                mod = self.project.by_name.get(alias)
                if mod is not None:
                    return ModuleRef(mod)
                sym = self.project.resolve(alias)
                if sym is not None:
                    return self._symbol_value(sym, alias)
            root = alias.split(".")[0]
            if alias in _EXTERNAL_ROOTS or root in (
                "jax", "numpy", "functools"
            ):
                return ExternalRef(alias)
            return Opaque(alias)
        memo_key = (id(module), name)
        if memo_key in self._global_memo:
            return self._global_memo[memo_key]
        self._global_memo[memo_key] = Opaque(name)  # cycle guard
        bind = resolve_name_binding(module, name, at)
        value: object = Opaque(name)
        if isinstance(bind, (ast.FunctionDef, ast.AsyncFunctionDef)):
            value = FuncVal(module, bind)
        elif isinstance(bind, ast.ClassDef):
            value = Opaque(f"{module.relpath}:{name}")
        elif isinstance(bind, ast.Assign):
            gframe = Frame(module)
            value = self.eval_expr(gframe, bind.value)
        elif isinstance(bind, ast.AnnAssign) and bind.value is not None:
            gframe = Frame(module)
            value = self.eval_expr(gframe, bind.value)
        if value is TOP:
            value = Opaque(name)
        self._global_memo[memo_key] = value
        return value

    def _symbol_value(self, sym, dotted: str) -> object:
        node = sym.node
        if node is None:
            return ModuleRef(sym.module)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return FuncVal(sym.module, node)
        if isinstance(node, ast.Assign):
            gframe = Frame(sym.module)
            return self.eval_expr(gframe, node.value)
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            gframe = Frame(sym.module)
            return self.eval_expr(gframe, node.value)
        return Opaque(dotted)

    _ARRAY_METHODS = {
        "reshape", "astype", "transpose", "sum", "mean", "max", "min",
        "prod", "argmax", "argmin", "squeeze", "ravel", "flatten",
        "copy", "all", "any", "var", "std", "take", "swapaxes",
    }

    def _eval_attribute(self, frame: Frame, node: ast.Attribute):
        base = self.eval_expr(frame, node.value)
        attr = node.attr
        if isinstance(base, ModuleRef):
            mod = base.module
            defs = (
                self.project.top_level(mod)
                if self.project is not None
                else {}
            )
            tnode = defs.get(attr)
            if isinstance(tnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return FuncVal(mod, tnode)
            if isinstance(tnode, ast.Assign):
                return self.eval_expr(Frame(mod), tnode.value)
            if isinstance(
                tnode, ast.AnnAssign
            ) and tnode.value is not None:
                return self.eval_expr(Frame(mod), tnode.value)
            alias = mod.aliases.get(attr)
            if alias is not None and self.project is not None:
                sub = self.project.by_name.get(alias)
                if sub is not None:
                    return ModuleRef(sub)
            return Opaque(f"{mod.relpath}:{attr}")
        if isinstance(base, ExternalRef):
            return ExternalRef(f"{base.dotted}.{attr}")
        if isinstance(base, Opaque):
            stored = frame.attrs.get((base.path, attr))
            if stored is not None:
                return stored
            owner = self._self_classes.get(base.path)
            if owner is not None:
                seeded = self.class_self_attrs(*owner).get(attr)
                if seeded is not None:
                    return seeded
                prop = self._property_getter(*owner, attr)
                if prop is not None:
                    return self.eval_function(
                        owner[0], prop, [], self_value=base
                    )
                method = self._plain_method(*owner, attr)
                if method is not None:
                    # A bound method: calling it evaluates the body
                    # with this self (quantize/astype helpers on the
                    # pool path stay precise).
                    return PartialVal(
                        func=FuncVal(owner[0], method),
                        args=(base,),
                        keywords={},
                    )
            return Opaque(f"{base.path}.{attr}")
        if isinstance(base, AbstractArray):
            if attr == "shape":
                return base.shape if isinstance(
                    base.shape, tuple
                ) else TOP
            if attr == "dtype":
                return base.dtype
            if attr == "ndim":
                return base.rank if base.rank is not None else TOP
            if attr == "size":
                if isinstance(base.shape, tuple):
                    t = total_size(base.shape)
                    return t if t is not None else TOP
                return TOP
            if attr == "T":
                if isinstance(base.shape, tuple):
                    return base.with_(shape=tuple(reversed(base.shape)))
                return base
            if attr == "at":
                return AtView(base)
            if attr in self._ARRAY_METHODS:
                return BoundMethod(base, attr)
            return TOP
        if isinstance(base, AtIndexed) and attr in (
            "set", "add", "multiply", "min", "max",
        ):
            return BoundMethod(base, attr)
        if isinstance(base, (tuple, list, ListRepeat)):
            return BoundMethod(base, attr)
        return TOP

    # -- subscripts ---------------------------------------------------------

    def _eval_subscript(self, frame: Frame, node: ast.Subscript):
        base = self.eval_expr(frame, node.value)
        if isinstance(base, AtView):
            shape = self._indexed_shape(frame, base.arr, node.slice)
            return AtIndexed(base.arr, shape)
        idx_node = node.slice
        if isinstance(base, (tuple, list)):
            if isinstance(idx_node, ast.Slice):
                lo = (
                    self.eval_expr(frame, idx_node.lower)
                    if idx_node.lower is not None else 0
                )
                hi = (
                    self.eval_expr(frame, idx_node.upper)
                    if idx_node.upper is not None else len(base)
                )
                if isinstance(lo, int) and isinstance(hi, int) and (
                    idx_node.step is None
                ):
                    return type(base)(base[lo:hi])
                return TOP
            idx = self.eval_expr(frame, idx_node)
            if isinstance(idx, int):
                try:
                    return base[idx]
                except IndexError:
                    return TOP
            return TOP
        if isinstance(base, Opaque):
            idx = self.eval_expr(frame, idx_node)
            if isinstance(idx, int):
                return Opaque(f"{base.path}[{idx}]")
            return TOP
        if isinstance(base, AbstractArray):
            shape = self._indexed_shape(frame, base, idx_node)
            return AbstractArray(shape=shape, dtype=base.dtype)
        return TOP

    def _indexed_shape(self, frame: Frame, arr: AbstractArray, idx_node):
        """Resulting shape of arr[<idx>]. numpy basic indexing for int /
        slice / None / Ellipsis items; advanced (array) indices are
        modeled only in the single-index and leading-batch cases."""
        if not isinstance(arr.shape, tuple):
            return TOP
        items = (
            list(idx_node.elts)
            if isinstance(idx_node, ast.Tuple)
            else [idx_node]
        )
        rank = len(arr.shape)
        # Walk left to right; bail to TOP on anything unmodeled. None
        # adds a dim, Ellipsis absorbs the unindexed middle; everything
        # else consumes one dim.
        out: List[object] = []
        pos = 0
        adv_shapes: List[object] = []
        ellipsis_seen = False
        n_real = sum(
            0 if (
                isinstance(it, ast.Constant)
                and (it.value is None or it.value is Ellipsis)
            ) else 1
            for it in items
        )
        if n_real > rank:
            # Only provable over-indexing when every subscript consumes
            # exactly one dim (a bool mask would consume several).
            plain = all(
                isinstance(it, (ast.Slice, ast.Constant))
                or not isinstance(
                    self.eval_expr(frame, it), AbstractArray
                )
                for it in items
            )
            if plain:
                self.geometry_error(
                    idx_node,
                    f"index with {n_real} subscripts into a rank-{rank}"
                    " array",
                )
            return TOP
        for it in items:
            if isinstance(it, ast.Constant) and it.value is None:
                out.append(Dim.const(1))
                continue
            if isinstance(it, ast.Constant) and it.value is Ellipsis:
                if ellipsis_seen:
                    return TOP
                ellipsis_seen = True
                take = rank - (n_real - pos)
                out.extend(arr.shape[pos:take])
                pos = take
                continue
            if isinstance(it, ast.Slice):
                dim = arr.shape[pos]
                pos += 1
                lo = (
                    self.eval_expr(frame, it.lower)
                    if it.lower is not None else None
                )
                hi = (
                    self.eval_expr(frame, it.upper)
                    if it.upper is not None else None
                )
                if it.step is not None:
                    out.append(TOP)
                elif lo is None and hi is None:
                    out.append(dim)
                else:
                    lo_d = as_dim(lo) if lo is not None else Dim.const(0)
                    hi_d = as_dim(hi) if hi is not None else dim
                    if not (
                        isinstance(lo_d, Dim) and isinstance(hi_d, Dim)
                    ):
                        out.append(TOP)
                        continue
                    lc, hc = lo_d.const_value, hi_d.const_value
                    if lc is not None and lc < 0:
                        out.append(TOP)  # negative start: unmodeled
                        continue
                    if hc is not None and hc < 0:
                        # x[: -k] -> dim - k (python semantics; exact
                        # only when k <= dim, else the size is 0 — a
                        # symbolic dim cannot rule that out, but the
                        # difference could never flip a provably_ne
                        # verdict from false to true spuriously for
                        # the in-range programs this models).
                        if isinstance(dim, Dim):
                            out.append(dim.add(hi_d))
                        else:
                            out.append(TOP)
                        continue
                    if (
                        hc is not None
                        and lc is not None
                        and isinstance(dim, Dim)
                        and dim.const_value is not None
                    ):
                        # BOTH ends concrete: python clamps. A symbolic
                        # start must fall through to the subtraction —
                        # treating it as 0 would fabricate a concrete
                        # size and a provably-false mismatch.
                        out.append(Dim.const(
                            max(0, min(hc, dim.const_value) - lc)
                        ))
                        continue
                    out.append(hi_d.sub(lo_d))
                continue
            value = self.eval_expr(frame, it)
            if isinstance(value, (int, Dim, ElementOf, Opaque)):
                pos += 1  # scalar index: consumes a dim
                continue
            if isinstance(value, AbstractArray):
                if value.dtype == "bool":
                    return TOP  # mask indexing flattens: unmodeled
                adv_shapes.append(value.shape)
                pos += 1
                continue
            return TOP
        out.extend(arr.shape[pos:])
        if adv_shapes:
            # Advanced indexing: the broadcast of the index arrays
            # replaces the consumed dims, prepended (numpy semantics for
            # the common leading-index case this repo uses).
            adv = adv_shapes[0]
            for s in adv_shapes[1:]:
                adv = broadcast_shapes(adv, s)
            if not isinstance(adv, tuple):
                return TOP
            return tuple(adv) + tuple(out)
        return tuple(out)

    # -- compare ------------------------------------------------------------

    def _eval_compare(self, frame: Frame, node: ast.Compare):
        if len(node.ops) != 1:
            return TOP
        left = self.eval_expr(frame, node.left)
        right = self.eval_expr(frame, node.comparators[0])
        op = node.ops[0]
        if isinstance(op, (ast.Is, ast.IsNot)):
            if right is None or left is None:
                known = left is None and right is None or (
                    left is None
                    and not isinstance(right, (Opaque, _Top))
                    and right is not None
                ) or (
                    right is None
                    and not isinstance(left, (Opaque, _Top))
                    and left is not None
                )
                if left is None and right is None:
                    result = True
                elif known:
                    result = False
                else:
                    return TOP
                return result if isinstance(op, ast.Is) else not result
            return TOP
        if isinstance(left, (int, bool)) and isinstance(
            right, (int, bool)
        ):
            try:
                if isinstance(op, ast.Eq):
                    return left == right
                if isinstance(op, ast.NotEq):
                    return left != right
                if isinstance(op, ast.Lt):
                    return left < right
                if isinstance(op, ast.LtE):
                    return left <= right
                if isinstance(op, ast.Gt):
                    return left > right
                if isinstance(op, ast.GtE):
                    return left >= right
            except TypeError:
                return TOP
        return TOP

    # -- binary ops ---------------------------------------------------------

    def binop(self, node: ast.AST, op: type, left, right):
        if isinstance(left, (list, ListRepeat)) or isinstance(
            right, (list, ListRepeat)
        ):
            return self._list_binop(op, left, right)
        if isinstance(left, tuple) and isinstance(right, tuple) and (
            op is ast.Add
        ):
            return left + right
        if isinstance(left, str) or isinstance(right, str):
            return TOP
        if isinstance(left, AbstractArray) or isinstance(
            right, AbstractArray
        ):
            return self._array_binop(node, op, left, right)
        ld, rd = as_dim(left), as_dim(right)
        if ld is TOP or rd is TOP:
            return TOP
        if isinstance(ld, ElementOf) or isinstance(rd, ElementOf):
            return self._elementof_binop(op, ld, rd)
        if op is ast.Add:
            return ld.add(rd)
        if op is ast.Sub:
            return ld.sub(rd)
        if op is ast.Mult:
            return ld.mul(rd)
        if op is ast.FloorDiv:
            return ld.floordiv(rd)
        if op is ast.Mod:
            return ld.mod(rd)
        if op is ast.Pow and ld.is_const and rd.is_const:
            try:
                return Dim.const(ld.const_value ** rd.const_value)
            except (OverflowError, ValueError):
                return TOP
        return TOP

    def _elementof_binop(self, op, ld, rd):
        if isinstance(ld, ElementOf) and isinstance(rd, Dim) and (
            rd.is_const
        ):
            c = rd.const_value
            if op is ast.Add:
                return ld.map(lambda v: v + c)
            if op is ast.Sub:
                return ld.map(lambda v: v - c)
            if op is ast.Mult:
                return ld.map(lambda v: v * c)
            if op is ast.FloorDiv and c != 0:
                return ld.map(lambda v: v // c)
            if op is ast.Mod and c != 0:
                return ld.map(lambda v: v % c)
        if isinstance(rd, ElementOf) and isinstance(ld, Dim) and (
            ld.is_const
        ):
            c = ld.const_value
            if op is ast.Add:
                return rd.map(lambda v: c + v)
            if op is ast.Sub:
                return rd.map(lambda v: c - v)
            if op is ast.Mult:
                return rd.map(lambda v: c * v)
        return TOP

    def _list_binop(self, op, left, right):
        if op is ast.Mult:
            lst, n = (left, right) if isinstance(
                left, (list, ListRepeat)
            ) else (right, left)
            nd = as_dim(n)
            if isinstance(lst, list) and len(lst) == 1 and nd is not TOP:
                return ListRepeat(lst[0], nd)
            if isinstance(lst, ListRepeat) and nd is not TOP:
                if isinstance(lst.length, Dim) and isinstance(nd, Dim):
                    return ListRepeat(lst.elem, lst.length.mul(nd))
            return TOP
        if op is ast.Add:
            if isinstance(left, list) and isinstance(right, list):
                return left + right
            ll = self._list_len(left)
            rl = self._list_len(right)
            if isinstance(ll, Dim) and isinstance(rl, Dim):
                elem = join(self._list_elem(left), self._list_elem(right))
                return ListRepeat(elem, ll.add(rl))
        return TOP

    @staticmethod
    def _list_len(v):
        if isinstance(v, list):
            return Dim.const(len(v))
        if isinstance(v, ListRepeat):
            return v.length if isinstance(v.length, Dim) else TOP
        return TOP

    @staticmethod
    def _list_elem(v):
        if isinstance(v, ListRepeat):
            return v.elem
        if isinstance(v, list) and v:
            out = v[0]
            for x in v[1:]:
                out = join(out, x)
            return out
        return TOP

    def _array_binop(self, node, op, left, right):
        if op is ast.MatMult:
            return self._matmul(node, left, right)
        la = left if isinstance(left, AbstractArray) else None
        ra = right if isinstance(right, AbstractArray) else None
        if la is not None and ra is not None:
            sink: List[str] = []
            shape = broadcast_shapes(la.shape, ra.shape, sink)
            self._flush_sink(node, sink)
            return AbstractArray(
                shape=shape, dtype=promote(la.dtype, ra.dtype)
            )
        arr = la or ra
        if arr is None:
            return TOP
        other = right if la is not None else left
        if isinstance(other, (int, Dim, ElementOf, bool)):
            # Weak python scalar: the array's dtype wins (jax semantics).
            return arr.with_()
        if isinstance(other, float):
            dt = arr.dtype
            if dt in ("int8", "int16", "int32", "int64", "bool"):
                dt = TOP  # weak-float promotion of int arrays varies
            return arr.with_(dtype=dt)
        return AbstractArray(shape=arr.shape, dtype=TOP)

    def _matmul(self, node, left, right):
        if not (
            isinstance(left, AbstractArray)
            and isinstance(right, AbstractArray)
        ):
            return TOP
        sa, sb = left.shape, right.shape
        if not isinstance(sa, tuple) or not isinstance(sb, tuple):
            return AbstractArray(shape=TOP, dtype=promote(
                left.dtype, right.dtype
            ))
        if len(sa) < 1 or len(sb) < 1:
            return TOP
        ka = sa[-1]
        kb = sb[-2] if len(sb) >= 2 else sb[-1]
        if dims_equal(ka, kb) is False:
            self.geometry_error(
                node,
                f"matmul contraction mismatch: {ka!r} (lhs last dim) vs "
                f"{kb!r}",
            )
        if len(sa) == 1 and len(sb) == 1:
            shape: object = ()
        elif len(sb) == 1:
            shape = sa[:-1]
        elif len(sa) == 1:
            shape = sb[:-2] + sb[-1:]
        else:
            sink: List[str] = []
            batch = broadcast_shapes(sa[:-2], sb[:-2], sink)
            self._flush_sink(node, sink)
            if not isinstance(batch, tuple):
                shape = TOP
            else:
                shape = batch + (sa[-2], sb[-1])
        return AbstractArray(
            shape=shape, dtype=promote(left.dtype, right.dtype)
        )

    # -- calls --------------------------------------------------------------

    def _eval_call(self, frame: Frame, node: ast.Call):
        module = frame.module
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in self._BUILTIN_INTRINSICS
            and node.func.id not in frame.env
            and node.func.id not in module.aliases
        ):
            args, kwargs = self._eval_call_args(frame, node)
            return self._builtin(frame, node, node.func.id, args, kwargs)
        func_value = self.eval_expr(frame, node.func)
        args, kwargs = self._eval_call_args(frame, node)

        # What the call TARGETS, syntactically: jit/pjit/shard_map are
        # recognized by dotted name too, so the project's own compat
        # shims (ray_tpu._private.jax_compat.shard_map) count.
        dotted_last = (module.dotted_name(node.func) or "").rsplit(
            ".", 1
        )[-1]

        # jax.jit(...) / pjit(...) construct a program value.
        if dotted_last in ("jit", "pjit"):
            program = self._jit_program_from_call(module, node)
            if program is not None:
                return program
            if isinstance(func_value, ExternalRef):
                return TOP
        if dotted_last == "shard_map" and isinstance(
            func_value, (ExternalRef, Opaque, FuncVal, _Top)
        ):
            return self._shard_map_from_call(
                frame, node, args, kwargs
            )

        if isinstance(func_value, JitProgram):
            return self._dispatch_jit(
                node, module, func_value.module, func_value.binding,
                args, kwargs,
            )
        # Fall back to the RTL5xx binding map for self-attr programs
        # (`self._prefill_fn(...)`) — the env cannot see __init__.
        if self.jit_resolver is not None and isinstance(
            func_value, (Opaque, _Top)
        ):
            resolved = self.jit_resolver(module, node)
            if resolved is not None:
                def_module, binding = resolved
                return self._dispatch_jit(
                    node, module, def_module, binding, args, kwargs
                )
        if isinstance(func_value, ShardMapProgram):
            if self.on_shard_call is not None:
                self.on_shard_call(node, module, func_value, args)
            return TOP
        if isinstance(func_value, PartialVal):
            if args is None:
                return TOP
            return self._call_value(
                frame, node, func_value.func,
                list(func_value.args) + list(args),
                {**func_value.keywords, **(kwargs or {})},
            )
        if isinstance(func_value, ExternalRef):
            return self._intrinsic(
                frame, node, func_value.dotted, args, kwargs
            )
        if isinstance(func_value, FuncVal):
            if args is None:
                return TOP
            return self.eval_function(
                func_value.module, func_value.fn, args, kwargs
            )
        if isinstance(func_value, BoundMethod):
            return self._method_call(frame, node, func_value, args, kwargs)
        return TOP

    def _call_value(self, frame, node, func_value, args, kwargs):
        if isinstance(func_value, FuncVal):
            return self.eval_function(
                func_value.module, func_value.fn, args, kwargs
            )
        if isinstance(func_value, ExternalRef):
            return self._intrinsic(
                frame, node, func_value.dotted, args, kwargs
            )
        return TOP

    def _eval_call_args(self, frame: Frame, node: ast.Call):
        """Returns (args, kwargs); args is None when a *splat of an
        unknown value makes the argument vector unmodelable."""
        args: List[object] = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                v = self.eval_expr(frame, a.value)
                if isinstance(v, (tuple, list)):
                    args.extend(v)
                else:
                    return None, {}
            else:
                args.append(self.eval_expr(frame, a))
        kwargs: Dict[str, object] = {}
        for kw in node.keywords:
            if kw.arg is None:
                v = self.eval_expr(frame, kw.value)
                if not isinstance(v, dict):
                    return None, {}
                continue
            kwargs[kw.arg] = self.eval_expr(frame, kw.value)
        return args, kwargs

    def _jit_program_from_call(
        self, module: ModuleInfo, node: ast.Call
    ) -> Optional[JitProgram]:
        from ray_tpu.tools.lint.rules_donation import (  # noqa: PLC0415
            _binding_from_wrapper_call,
        )

        binding = _binding_from_wrapper_call(module, node)
        if binding is None:
            return None
        return JitProgram(module=module, binding=binding)

    def _shard_map_from_call(self, frame, node, args, kwargs):
        fn_value: object = TOP
        if args:
            fn_value = args[0]
        elif node.args:
            fn_value = self.eval_expr(frame, node.args[0])
        mesh = (kwargs or {}).get("mesh", TOP)
        in_specs = (kwargs or {}).get("in_specs", TOP)
        if not isinstance(mesh, AbstractMesh):
            mesh = TOP
        return ShardMapProgram(
            module=frame.module,
            fn_value=fn_value if isinstance(fn_value, FuncVal) else TOP,
            mesh=mesh,
            in_specs=in_specs if isinstance(in_specs, tuple) else TOP,
            call=node,
        )

    def _dispatch_jit(
        self, node, module, def_module, binding, args, kwargs
    ):
        if self.on_jit_call is not None:
            return self.on_jit_call(
                node, module, def_module, binding, args, kwargs
            )
        return TOP

    def eval_jit_body(
        self, def_module, binding, args, kwargs
    ) -> object:
        """Evaluate a jit-wrapped function with call-site arguments —
        the caller (rules) brackets this with an error-sink marker."""
        fn = binding.fn
        if fn is None or args is None:
            return TOP
        params = [p.arg for p in (*fn.args.posonlyargs, *fn.args.args)]
        self_value = None
        if params and params[0] in ("self", "cls") and (
            len(args) == len(params) - 1
            or (len(args) < len(params) - 1 and (kwargs or fn.args.defaults))
        ):
            self_value = self.self_token(def_module, fn)
        return self.eval_function(
            def_module, fn, args, kwargs, self_value=self_value
        )

    # -- intrinsics ---------------------------------------------------------

    def _intrinsic(self, frame, node, dotted, args, kwargs):
        last = dotted.rsplit(".", 1)[-1]
        kwargs = kwargs or {}
        if args is None:
            return TOP
        a0 = args[0] if args else None

        if last == "partial" and args:
            return PartialVal(
                func=args[0], args=tuple(args[1:]), keywords=kwargs
            )
        if last in ("zeros", "ones", "empty", "full"):
            shape = as_shape(a0) if a0 is not None else TOP
            dt_arg = None
            if last == "full":
                dt_arg = args[2] if len(args) > 2 else kwargs.get("dtype")
            else:
                dt_arg = args[1] if len(args) > 1 else kwargs.get("dtype")
            if dt_arg is not None:
                dtype = dtype_of(dt_arg)
            elif dotted.startswith("numpy."):
                dtype = "float64"  # numpy's default differs from jax's
            else:
                dtype = "float32"
            return AbstractArray(shape=shape, dtype=dtype)
        if last in ("zeros_like", "ones_like", "empty_like", "full_like"):
            if isinstance(a0, AbstractArray):
                dt_arg = kwargs.get("dtype")
                dtype = dtype_of(dt_arg) if dt_arg is not None else (
                    a0.dtype
                )
                return AbstractArray(shape=a0.shape, dtype=dtype)
            return TOP
        if last in ("asarray", "array"):
            dt_arg = args[1] if len(args) > 1 else kwargs.get("dtype")
            dtype = dtype_of(dt_arg) if dt_arg is not None else TOP
            if isinstance(a0, AbstractArray):
                return a0.with_(
                    dtype=dtype if dt_arg is not None else a0.dtype
                )
            if isinstance(a0, ListRepeat):
                return AbstractArray(shape=(a0.length,), dtype=dtype)
            if isinstance(a0, (list, tuple)):
                if all(
                    isinstance(v, (int, float, Dim, ElementOf, bool))
                    for v in a0
                ):
                    return AbstractArray(
                        shape=(Dim.const(len(a0)),), dtype=dtype
                    )
                return AbstractArray(shape=TOP, dtype=dtype)
            if isinstance(a0, (int, Dim, ElementOf)):
                return AbstractArray(shape=(), dtype=dtype)
            return AbstractArray(shape=TOP, dtype=dtype)
        if last == "arange":
            if len(args) == 1:
                d = as_dim(a0)
                if d is not TOP:
                    return AbstractArray(shape=(d,), dtype="int32")
            return AbstractArray(shape=TOP, dtype="int32")
        if last == "reshape" and dotted.split(".")[0] in (
            "jax", "numpy"
        ):
            if isinstance(a0, AbstractArray):
                shape_arg = args[1] if len(args) > 1 else kwargs.get(
                    "newshape", kwargs.get("shape")
                )
                return self._reshape(node, a0, as_shape(shape_arg))
            return TOP
        if last == "transpose":
            if isinstance(a0, AbstractArray):
                axes = args[1] if len(args) > 1 else kwargs.get("axes")
                return self._transpose(a0, axes)
            return TOP
        if last in ("concatenate", "concat"):
            return self._concatenate(node, a0, args, kwargs)
        if last == "stack":
            return self._stack(node, a0, args, kwargs)
        if last in ("matmul", "dot"):
            if len(args) >= 2:
                return self._matmul(node, args[0], args[1])
            return TOP
        if last == "einsum":
            return self._einsum(node, args)
        if last == "where":
            if len(args) == 3:
                sink: List[str] = []
                arrs = [a for a in args if isinstance(a, AbstractArray)]
                if not arrs:
                    return TOP
                shape = arrs[0].shape
                for a in arrs[1:]:
                    shape = broadcast_shapes(shape, a.shape, sink)
                self._flush_sink(node, sink)
                dtypes = [
                    a.dtype for a in args[1:]
                    if isinstance(a, AbstractArray)
                ]
                dtype = dtypes[0] if dtypes else TOP
                for d in dtypes[1:]:
                    dtype = promote(dtype, d)
                return AbstractArray(shape=shape, dtype=dtype)
            return TOP
        if last in _REDUCTIONS:
            if isinstance(a0, AbstractArray):
                axis = args[1] if len(args) > 1 else kwargs.get("axis")
                return self._reduce(a0, last, axis, kwargs)
            return TOP
        if last in ("expand_dims",):
            if isinstance(a0, AbstractArray) and isinstance(
                a0.shape, tuple
            ):
                axis = args[1] if len(args) > 1 else kwargs.get("axis")
                if isinstance(axis, int):
                    r = len(a0.shape) + 1
                    ax = axis if axis >= 0 else axis + r
                    if 0 <= ax <= len(a0.shape):
                        return a0.with_(shape=(
                            a0.shape[:ax] + (Dim.const(1),)
                            + a0.shape[ax:]
                        ))
            return TOP
        if last == "squeeze":
            if isinstance(a0, AbstractArray):
                return self._squeeze(a0, args[1:] or kwargs.get("axis"))
            return TOP
        if last == "broadcast_to":
            if isinstance(a0, AbstractArray) and len(args) > 1:
                target = as_shape(args[1])
                if isinstance(target, tuple) and isinstance(
                    a0.shape, tuple
                ):
                    sink: List[str] = []
                    broadcast_shapes(a0.shape, target, sink)
                    self._flush_sink(node, sink)
                return AbstractArray(shape=target, dtype=a0.dtype)
            return TOP
        if last == "dynamic_slice":
            if isinstance(a0, AbstractArray) and len(args) >= 3:
                sizes = as_shape(args[2])
                return AbstractArray(shape=sizes, dtype=a0.dtype)
            return TOP
        if last == "dynamic_update_slice":
            if isinstance(a0, AbstractArray) and len(args) >= 2 and (
                isinstance(args[1], AbstractArray)
            ):
                upd = args[1]
                if isinstance(a0.shape, tuple) and isinstance(
                    upd.shape, tuple
                ):
                    if len(upd.shape) != len(a0.shape):
                        self.geometry_error(
                            node,
                            "dynamic_update_slice update rank "
                            f"{len(upd.shape)} != operand rank "
                            f"{len(a0.shape)}",
                        )
                return a0.with_()
            return TOP
        if last == "take":
            if isinstance(a0, AbstractArray):
                return AbstractArray(shape=TOP, dtype=a0.dtype)
            return TOP
        if last == "device_put":
            arr = a0
            sharding = args[1] if len(args) > 1 else kwargs.get(
                "device"
            )
            if isinstance(sharding, ShardingVal) and (
                self.on_sharding_apply is not None
            ):
                self.on_sharding_apply(node, frame.module, arr, sharding)
            if isinstance(arr, AbstractArray):
                if isinstance(sharding, ShardingVal):
                    return dataclasses.replace(arr, sharding=sharding)
                return arr
            return TOP
        if last == "with_sharding_constraint":
            arr = a0
            sharding = args[1] if len(args) > 1 else kwargs.get(
                "shardings"
            )
            if isinstance(sharding, ShardingVal) and (
                self.on_sharding_apply is not None
            ):
                self.on_sharding_apply(node, frame.module, arr, sharding)
            if isinstance(arr, AbstractArray):
                return arr
            return TOP
        if last == "Mesh":
            names_val = args[1] if len(args) > 1 else kwargs.get(
                "axis_names"
            )
            names: object = TOP
            if isinstance(names_val, str):
                names = (names_val,)
            elif isinstance(names_val, (tuple, list)) and all(
                isinstance(v, str) for v in names_val
            ):
                names = tuple(names_val)
            sizes: object = TOP
            if isinstance(a0, AbstractArray) and shape_fully_known(
                a0.shape
            ):
                consts = [d.const_value for d in a0.shape]
                if all(c is not None for c in consts):
                    sizes = tuple(consts)
            return AbstractMesh(names=names, sizes=sizes)
        if last == "create_device_mesh":
            shape = as_shape(a0) if a0 is not None else TOP
            return AbstractArray(shape=shape, dtype=TOP)
        if last in ("PartitionSpec", "P"):
            entries: List[object] = []
            for a in args:
                if a is None or isinstance(a, str):
                    entries.append((a,) if isinstance(a, str) else None)
                elif isinstance(a, (tuple, list)) and all(
                    isinstance(v, str) for v in a
                ):
                    entries.append(tuple(a))
                else:
                    entries.append(TOP)
            return SpecVal(entries=tuple(entries))
        if last == "NamedSharding":
            mesh = a0 if isinstance(a0, AbstractMesh) else TOP
            spec = args[1] if len(args) > 1 else kwargs.get("spec")
            return ShardingVal(
                mesh=mesh,
                spec=spec if isinstance(spec, SpecVal) else TOP,
            )
        if last == "astype":
            if isinstance(a0, AbstractArray) and len(args) > 1:
                return a0.with_(dtype=dtype_of(args[1]))
            return TOP
        if last in _ELEMENTWISE_UNARY:
            if isinstance(a0, AbstractArray):
                return a0.with_()
            return TOP
        if last in _ELEMENTWISE_BINARY:
            if len(args) >= 2:
                return self._array_binop(
                    node, ast.Add, args[0], args[1]
                )
            return TOP
        if last in DTYPE_NAMES:
            # jnp.int32(x): a 0-d cast — keep the scalar value usable in
            # shape arithmetic.
            if isinstance(a0, (int, Dim, ElementOf)):
                return a0
            if isinstance(a0, AbstractArray):
                return a0.with_(dtype=last)
            return TOP
        if dotted.startswith("jax.random."):
            return AbstractArray(shape=TOP, dtype=TOP)
        return TOP

    # -- builtins as intrinsics --------------------------------------------

    _BUILTIN_INTRINSICS = {
        "len", "min", "max", "int", "float", "range", "enumerate",
        "zip", "sum", "abs", "sorted", "tuple", "list",
    }

    def _builtin(self, frame, node, name, args, kwargs):
        if args is None:
            return TOP
        a0 = args[0] if args else None
        if name == "len":
            if isinstance(a0, (tuple, list)):
                return len(a0)
            if isinstance(a0, ListRepeat):
                return a0.length
            if isinstance(a0, AbstractArray) and isinstance(
                a0.shape, tuple
            ) and a0.shape:
                return a0.shape[0]
            if isinstance(a0, Opaque):
                return Dim.symbol(f"len({a0.path})")
            return TOP
        if name in ("min", "max"):
            flat = args[0] if len(args) == 1 and isinstance(
                args[0], (tuple, list)
            ) else args
            if all(isinstance(v, int) for v in flat) and flat:
                return min(flat) if name == "min" else max(flat)
            return TOP
        if name in ("int", "float"):
            if isinstance(a0, (int, float, Dim, ElementOf)):
                return a0
            return TOP
        if name == "abs":
            if isinstance(a0, int):
                return abs(a0)
            return TOP
        if name == "tuple":
            if isinstance(a0, (tuple, list)):
                return tuple(a0)
            return TOP
        if name == "list":
            if isinstance(a0, (tuple, list)):
                return list(a0)
            return TOP
        if name == "enumerate":
            if isinstance(a0, (tuple, list)):
                return tuple((i, v) for i, v in enumerate(a0))
            return TOP
        if name == "zip":
            if all(isinstance(a, (tuple, list)) for a in args):
                return tuple(zip(*args))
            return TOP
        if name == "sum":
            if isinstance(a0, (tuple, list)) and all(
                isinstance(v, (int, Dim)) for v in a0
            ):
                out: object = Dim.const(0)
                for v in a0:
                    out = out.add(as_dim(v))
                return out
            return TOP
        return TOP

    # -- array method calls -------------------------------------------------

    def _method_call(self, frame, node, bm: BoundMethod, args, kwargs):
        recv = bm.recv
        if args is None:
            return TOP
        if isinstance(recv, AtIndexed):
            if bm.name in ("set", "add", "multiply", "min", "max"):
                if args and isinstance(recv.index_shape, tuple):
                    value = args[0]
                    if isinstance(value, AbstractArray) and isinstance(
                        value.shape, tuple
                    ):
                        sink: List[str] = []
                        broadcast_shapes(
                            recv.index_shape, value.shape, sink
                        )
                        for msg in sink:
                            self.geometry_error(
                                node,
                                f".at[...].{bm.name} value shape "
                                f"{value.shape} does not fit the "
                                f"indexed region {recv.index_shape}: "
                                + msg,
                            )
                        # A provably larger update can never fit.
                        if len(value.shape) > len(recv.index_shape):
                            self.geometry_error(
                                node,
                                f".at[...].{bm.name} value rank "
                                f"{len(value.shape)} exceeds indexed "
                                f"region rank {len(recv.index_shape)}",
                            )
                return recv.arr.with_()
            return TOP
        if isinstance(recv, AbstractArray):
            if bm.name == "reshape":
                shape_arg: object
                if len(args) == 1:
                    shape_arg = args[0]
                else:
                    shape_arg = tuple(args)
                return self._reshape(node, recv, as_shape(shape_arg))
            if bm.name == "astype":
                if args:
                    return recv.with_(dtype=dtype_of(args[0]))
                return TOP
            if bm.name == "transpose":
                axes = args if args else kwargs.get("axes")
                if axes and len(axes) == 1 and isinstance(
                    axes[0], (tuple, list)
                ):
                    axes = tuple(axes[0])
                return self._transpose(recv, axes or None)
            if bm.name == "swapaxes":
                if len(args) == 2 and isinstance(
                    recv.shape, tuple
                ) and all(isinstance(a, int) for a in args):
                    shape = list(recv.shape)
                    i, j = args
                    try:
                        shape[i], shape[j] = shape[j], shape[i]
                    except IndexError:
                        return TOP
                    return recv.with_(shape=tuple(shape))
                return TOP
            if bm.name in ("ravel", "flatten"):
                if isinstance(recv.shape, tuple):
                    t = total_size(recv.shape)
                    if t is not None:
                        return recv.with_(shape=(t,))
                return AbstractArray(shape=TOP, dtype=recv.dtype)
            if bm.name == "copy":
                return recv.with_()
            if bm.name in _REDUCTIONS:
                axis = args[0] if args else kwargs.get("axis")
                return self._reduce(recv, bm.name, axis, kwargs)
            if bm.name == "take":
                return AbstractArray(shape=TOP, dtype=recv.dtype)
            if bm.name == "squeeze":
                return self._squeeze(recv, args or kwargs.get("axis"))
        return TOP

    # -- shared shape ops ---------------------------------------------------

    def _reshape(self, node, arr: AbstractArray, new_shape):
        if not isinstance(new_shape, tuple):
            return AbstractArray(shape=TOP, dtype=arr.dtype)
        # Resolve a single -1 when everything else is known.
        dims = list(new_shape)
        minus_one = [
            i for i, d in enumerate(dims)
            if isinstance(d, Dim) and d.is_const and d.const_value == -1
        ]
        if minus_one:
            if len(minus_one) > 1:
                return AbstractArray(shape=TOP, dtype=arr.dtype)
            if isinstance(arr.shape, tuple):
                total = total_size(arr.shape)
                rest = total_size(
                    [d for i, d in enumerate(dims) if i != minus_one[0]]
                )
                if total is not None and rest is not None:
                    dims[minus_one[0]] = total.floordiv(rest)
                    if dims[minus_one[0]] is TOP:
                        dims[minus_one[0]] = TOP
                else:
                    dims[minus_one[0]] = TOP
            else:
                dims[minus_one[0]] = TOP
        elif isinstance(arr.shape, tuple):
            told = total_size(arr.shape)
            tnew = total_size(dims)
            if told is not None and tnew is not None and (
                told.provably_ne(tnew)
            ):
                self.geometry_error(
                    node,
                    f"reshape from {arr.shape} (size {told!r}) to "
                    f"{tuple(dims)} (size {tnew!r}) changes the "
                    "element count",
                )
        return AbstractArray(shape=tuple(dims), dtype=arr.dtype)

    def _transpose(self, arr: AbstractArray, axes):
        if not isinstance(arr.shape, tuple):
            return arr
        if axes is None:
            return arr.with_(shape=tuple(reversed(arr.shape)))
        if isinstance(axes, (tuple, list)) and all(
            isinstance(a, int) for a in axes
        ) and sorted(axes) == list(range(len(arr.shape))):
            return arr.with_(
                shape=tuple(arr.shape[a] for a in axes)
            )
        return AbstractArray(shape=TOP, dtype=arr.dtype)

    def _reduce(self, arr: AbstractArray, name, axis, kwargs):
        dtype = (
            "int32" if name in ("argmax", "argmin")
            else "bool" if name in ("all", "any")
            else arr.dtype
        )
        if not isinstance(arr.shape, tuple):
            return AbstractArray(shape=TOP, dtype=dtype)
        keep = kwargs.get("keepdims") is True
        if axis is None:
            return AbstractArray(
                shape=tuple(Dim.const(1) for _ in arr.shape)
                if keep else (),
                dtype=dtype,
            )
        axes = axis if isinstance(axis, (tuple, list)) else [axis]
        if not all(isinstance(a, int) for a in axes):
            return AbstractArray(shape=TOP, dtype=dtype)
        rank = len(arr.shape)
        norm = {a if a >= 0 else a + rank for a in axes}
        if not all(0 <= a < rank for a in norm):
            return AbstractArray(shape=TOP, dtype=dtype)
        shape = tuple(
            Dim.const(1) if i in norm and keep else d
            for i, d in enumerate(arr.shape)
            if keep or i not in norm
        )
        return AbstractArray(shape=shape, dtype=dtype)

    def _squeeze(self, arr: AbstractArray, axis):
        if not isinstance(arr.shape, tuple):
            return arr
        if axis in (None, (), []):
            if all(
                isinstance(d, Dim) and d.is_const for d in arr.shape
            ):
                return arr.with_(shape=tuple(
                    d for d in arr.shape if d.const_value != 1
                ))
            return AbstractArray(shape=TOP, dtype=arr.dtype)
        axes = axis if isinstance(axis, (tuple, list)) else [axis]
        if all(isinstance(a, int) for a in axes):
            rank = len(arr.shape)
            norm = {a if a >= 0 else a + rank for a in axes}
            if all(0 <= a < rank for a in norm):
                return arr.with_(shape=tuple(
                    d for i, d in enumerate(arr.shape) if i not in norm
                ))
        return AbstractArray(shape=TOP, dtype=arr.dtype)

    def _concatenate(self, node, a0, args, kwargs):
        if not isinstance(a0, (tuple, list)):
            return TOP
        arrs = [a for a in a0 if isinstance(a, AbstractArray)]
        if len(arrs) != len(a0) or not arrs:
            return TOP
        axis = args[1] if len(args) > 1 else kwargs.get("axis", 0)
        if not isinstance(axis, int):
            return AbstractArray(shape=TOP, dtype=TOP)
        shapes = [a.shape for a in arrs]
        if not all(isinstance(s, tuple) for s in shapes):
            return AbstractArray(shape=TOP, dtype=TOP)
        rank = len(shapes[0])
        if any(len(s) != rank for s in shapes):
            self.geometry_error(
                node, "concatenate of arrays with different ranks"
            )
            return AbstractArray(shape=TOP, dtype=TOP)
        ax = axis if axis >= 0 else axis + rank
        if not 0 <= ax < rank:
            return AbstractArray(shape=TOP, dtype=TOP)
        out: List[object] = []
        for i in range(rank):
            if i == ax:
                acc: object = shapes[0][i]
                for s in shapes[1:]:
                    if isinstance(acc, Dim) and isinstance(s[i], Dim):
                        acc = acc.add(s[i])
                    else:
                        acc = TOP
                out.append(acc)
            else:
                d = shapes[0][i]
                for s in shapes[1:]:
                    if dims_equal(d, s[i]) is False:
                        self.geometry_error(
                            node,
                            f"concatenate dim {i} mismatch: {d!r} vs "
                            f"{s[i]!r} (only the concat axis may "
                            "differ)",
                        )
                    d = d if dims_equal(d, s[i]) else join_dim(d, s[i])
                out.append(d)
        dtype = arrs[0].dtype
        for a in arrs[1:]:
            dtype = promote(dtype, a.dtype)
        return AbstractArray(shape=tuple(out), dtype=dtype)

    def _stack(self, node, a0, args, kwargs):
        if not isinstance(a0, (tuple, list)):
            return TOP
        arrs = [a for a in a0 if isinstance(a, AbstractArray)]
        if len(arrs) != len(a0) or not arrs:
            return TOP
        shapes = [a.shape for a in arrs]
        if not all(isinstance(s, tuple) for s in shapes):
            return AbstractArray(shape=TOP, dtype=TOP)
        rank = len(shapes[0])
        if any(len(s) != rank for s in shapes):
            self.geometry_error(
                node, "stack of arrays with different ranks"
            )
            return AbstractArray(shape=TOP, dtype=TOP)
        for i in range(rank):
            for s in shapes[1:]:
                if dims_equal(shapes[0][i], s[i]) is False:
                    self.geometry_error(
                        node,
                        f"stack dim {i} mismatch: {shapes[0][i]!r} vs "
                        f"{s[i]!r}",
                    )
        axis = args[1] if len(args) > 1 else kwargs.get("axis", 0)
        if not isinstance(axis, int):
            return AbstractArray(shape=TOP, dtype=TOP)
        ax = axis if axis >= 0 else axis + rank + 1
        if not 0 <= ax <= rank:
            return AbstractArray(shape=TOP, dtype=TOP)
        base = list(shapes[0])
        base.insert(ax, Dim.const(len(arrs)))
        dtype = arrs[0].dtype
        for a in arrs[1:]:
            dtype = promote(dtype, a.dtype)
        return AbstractArray(shape=tuple(base), dtype=dtype)

    def _einsum(self, node, args):
        if not args or not isinstance(args[0], str):
            return TOP
        eq = args[0].replace(" ", "")
        operands = args[1:]
        if "..." in eq or "->" not in eq:
            return TOP
        lhs, rhs = eq.split("->")
        in_specs = lhs.split(",")
        if len(in_specs) != len(operands):
            return TOP
        sizes: Dict[str, object] = {}
        for spec, op in zip(in_specs, operands):
            if not isinstance(op, AbstractArray):
                return TOP
            if not isinstance(op.shape, tuple):
                continue
            if len(spec) != len(op.shape):
                self.geometry_error(
                    node,
                    f"einsum operand spec '{spec}' has {len(spec)} "
                    f"indices but the operand is rank {len(op.shape)}",
                )
                return AbstractArray(shape=TOP, dtype=TOP)
            for letter, dim in zip(spec, op.shape):
                prev = sizes.get(letter)
                if prev is None:
                    sizes[letter] = dim
                elif dims_equal(prev, dim) is False:
                    self.geometry_error(
                        node,
                        f"einsum index '{letter}' has conflicting "
                        f"sizes {prev!r} and {dim!r}",
                    )
        out_shape = tuple(sizes.get(letter, TOP) for letter in rhs)
        dtype: object = TOP
        arrs = [
            op for op in operands if isinstance(op, AbstractArray)
        ]
        if arrs:
            dtype = arrs[0].dtype
            for a in arrs[1:]:
                dtype = promote(dtype, a.dtype)
        return AbstractArray(shape=out_shape, dtype=dtype)
