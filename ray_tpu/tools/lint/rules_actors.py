"""Family 7 — actor call-graph deadlock rules.

Actors in this runtime execute one task at a time unless deployed with
`max_concurrency`/async methods. A method that BLOCKS on the result of a
task that can only run on an actor that is (transitively) waiting on the
caller never completes — the classic distributed deadlock. Because the
layering rule keeps all distribution in actors + collectives (PAPER.md
§1), the hazard is a static property of the actor call graph, which the
project-level pass can build:

RTL701: a blocking `ray_tpu.get` inside an actor method on a ref whose
producing task targets the SAME actor class — the self-cycle. The
producing task queues behind the very method that is waiting for it.

RTL702: synchronous cross-actor call cycles (A.m gets B.n, B.n gets
A.p). Detected as strongly-connected components of the blocking-call
graph between actor classes; every blocking edge inside a cycle is
flagged. Handles resolve through class-wide `self._x = Cls.remote()`
assignments, method-local bindings, `ray_tpu.remote(Cls)` registrations
(aliased imports included), and `functools.partial`-bound remote
methods; an unresolvable handle contributes no edge (conservative).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.lint.core import (
    Finding,
    ModuleInfo,
    Rule,
    _scope_level_nodes,
    resolve_name_binding,
)
from ray_tpu.tools.lint.project import qualkey

GET_TARGETS = ("ray_tpu.get", "ray_tpu.api.get")


@dataclasses.dataclass
class Edge:
    src: Tuple[str, str]  # actor class qualkey
    dst: Tuple[str, str]
    module: ModuleInfo  # module holding the get call
    node: ast.AST  # the blocking get
    src_method: str
    dst_method: str


def _is_remote_task_call(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "remote"
    )


def _blocking_gets(
    index, module: ModuleInfo, attr_handles, fn: ast.AST
):
    """(get_call, resolved (dst_key, dst_method)) pairs in one function."""
    for call in _scope_level_nodes(fn):
        if not isinstance(call, ast.Call):
            continue
        if module.dotted_name(call.func) not in GET_TARGETS:
            continue
        if not call.args:
            continue
        for target in _ref_targets(
            index, module, attr_handles, call.args[0], call
        ):
            yield call, target


def _actor_edges(project) -> List[Edge]:
    """All blocking-get edges between actor classes, project-wide.

    Edges come from two places: blocking gets written directly in an
    actor's (sync) methods, and blocking gets in plain functions those
    methods REACH through the project call graph — the actor-method
    reachability index. A helper that resolves a task to an actor class
    contributes the edge to every actor whose methods can reach it."""
    cached = project.memo.get("actor_edges")
    if cached is not None:
        return cached
    index = project.actor_index()
    if not index.classes:
        # No actor classes anywhere in the scan: no edges, and the
        # (expensive) project call graph need not be built at all —
        # this keeps diff-scoped runs over actor-free modules fast.
        project.memo["actor_edges"] = []
        return []
    graph = project.call_graph()
    fn_index = project.function_index()
    method_owner = {}  # method qualkey -> actor class key
    for key, (module, cls) in index.classes.items():
        for m in cls.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                method_owner[qualkey(module, m)] = key
    edges: List[Edge] = []
    for key, (module, cls) in index.classes.items():
        attr_handles = _class_attr_handles(index, module, cls)
        reached: List[Tuple[Tuple[str, str], str]] = []
        seen = set()
        for method in cls.body:
            if not isinstance(method, ast.FunctionDef):
                continue  # async methods don't hard-block the actor loop
            for call, target in _blocking_gets(
                index, module, attr_handles, method
            ):
                dst_key, dst_method = target
                edges.append(
                    Edge(
                        src=key, dst=dst_key, module=module, node=call,
                        src_method=method.name, dst_method=dst_method,
                    )
                )
            mk = qualkey(module, method)
            seen.add(mk)
            reached.append((mk, method.name))
        # BFS over the call graph: helpers this actor's methods reach run
        # ON the actor, so their blocking gets block the actor loop too.
        frontier = list(reached)
        while frontier:
            k, via = frontier.pop()
            for callee in sorted(graph.get(k, ())):
                if callee in seen:
                    continue
                seen.add(callee)
                if callee in method_owner:
                    continue  # another actor's method: scanned there
                entry = fn_index.get(callee)
                if entry is None:
                    continue
                hmod, hfn = entry
                if isinstance(hfn, ast.AsyncFunctionDef):
                    continue
                # Handle inference inside a free helper sees its own
                # local bindings + module registrations, never this
                # actor's self attrs (the helper has no self).
                for call, target in _blocking_gets(index, hmod, {}, hfn):
                    dst_key, dst_method = target
                    edges.append(
                        Edge(
                            src=key, dst=dst_key, module=hmod, node=call,
                            src_method=f"{via} (via {callee[1]})",
                            dst_method=dst_method,
                        )
                    )
                frontier.append((callee, via))
    project.memo["actor_edges"] = edges
    return edges


def _class_attr_handles(
    index, module: ModuleInfo, cls: ast.ClassDef
) -> Dict[str, Tuple[str, str]]:
    """self attrs holding a handle whose actor class is provable."""
    out: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        key = None
        if _is_remote_task_call(node.value) or isinstance(
            node.value, ast.Call
        ):
            key = index.handle_class(module, node.value, node)
        if key is None:
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out[t.attr] = key
    return out


def _ref_targets(
    index,
    module: ModuleInfo,
    attr_handles: Dict[str, Tuple[str, str]],
    expr: ast.AST,
    at: ast.AST,
) -> List[Tuple[Tuple[str, str], str]]:
    """(actor class, method name) for every resolvable actor-task ref in
    a get argument (single ref, list of refs, name bound earlier,
    partial-bound remote method)."""
    out: List[Tuple[Tuple[str, str], str]] = []
    for node in ast.walk(expr):
        resolved = _resolve_ref_value(
            index, module, attr_handles, node, at
        )
        if resolved is not None:
            out.append(resolved)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            bind = resolve_name_binding(module, node.id, at)
            if isinstance(bind, ast.Assign):
                resolved = _resolve_ref_value(
                    index, module, attr_handles, bind.value, bind
                )
                if resolved is not None:
                    out.append(resolved)
    return out


def _resolve_ref_value(
    index,
    module: ModuleInfo,
    attr_handles: Dict[str, Tuple[str, str]],
    value: ast.AST,
    at: ast.AST,
    _depth: int = 0,
) -> Optional[Tuple[Tuple[str, str], str]]:
    """A ref-producing expression: a direct `<handle>.<m>.remote(...)`,
    a call of a name bound to one, or a call of a `functools.partial`-
    bound remote method (`fire = partial(h.m.remote, x); fire()`)."""
    if _depth > 4:
        return None
    resolved = _task_target(index, module, attr_handles, value, at)
    if resolved is not None:
        return resolved
    if not isinstance(value, ast.Call):
        return None
    dotted = module.dotted_name(value.func) or ""
    if dotted.rsplit(".", 1)[-1] == "partial" and value.args:
        return _resolve_ref_value(
            index,
            module,
            attr_handles,
            ast.Call(func=value.args[0], args=[], keywords=[]),
            at,
            _depth + 1,
        )
    if isinstance(value.func, ast.Name):
        bind = resolve_name_binding(module, value.func.id, at)
        if isinstance(bind, ast.Assign):
            # Calling a name bound to a partial invokes the partial's
            # underlying remote method.
            return _resolve_ref_value(
                index, module, attr_handles, bind.value, bind, _depth + 1
            )
    return None


def _task_target(
    index,
    module: ModuleInfo,
    attr_handles: Dict[str, Tuple[str, str]],
    node: ast.AST,
    at: ast.AST,
) -> Optional[Tuple[Tuple[str, str], str]]:
    """`<handle>.<method>.remote(...)` -> (actor class, method)."""
    if not _is_remote_task_call(node):
        return None
    base = node.func.value
    if isinstance(node.func, ast.Attribute) and isinstance(
        base, ast.Attribute
    ):
        method_name = base.attr
        handle = base.value
        key = None
        if (
            isinstance(handle, ast.Attribute)
            and isinstance(handle.value, ast.Name)
            and handle.value.id == "self"
        ):
            key = attr_handles.get(handle.attr)
        elif isinstance(handle, ast.Name):
            bind = resolve_name_binding(module, handle.id, at)
            if isinstance(bind, ast.Assign):
                key = index.handle_class(module, bind.value, bind)
            elif bind is None:
                # Only an UNBOUND name may fall back to the registration
                # map; a local binding we couldn't resolve shadows any
                # module-level registration of the same name.
                key = index.registered.get((module.relpath, handle.id))
        if key is not None:
            return (key, method_name)
    return None


def _sccs(graph: Dict[Tuple, Set[Tuple]]) -> List[Set[Tuple]]:
    """Tarjan strongly-connected components (iterative)."""
    idx: Dict[Tuple, int] = {}
    low: Dict[Tuple, int] = {}
    on_stack: Set[Tuple] = set()
    stack: List[Tuple] = []
    out: List[Set[Tuple]] = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)

    nodes = set(graph) | {w for vs in graph.values() for w in vs}
    for v in sorted(nodes):
        if v not in idx:
            strongconnect(v)
    return out


class SameActorBlockingGetRule(Rule):
    id = "RTL701"
    name = "same-actor-blocking-get"
    family = "actors"
    description = (
        "blocking ray_tpu.get inside an actor method on a task of the "
        "same actor — the producing task queues behind the waiter"
    )
    rationale = (
        "an actor executes one task at a time: a method that blocks on "
        "ray_tpu.get of a task targeting its own actor waits for work "
        "that can only start after the method returns. The get never "
        "completes (or burns the full timeout). Make the method async "
        "and await the ref, return the ref to the caller, or route the "
        "work through a different actor."
    )
    bad_example = """
        import ray_tpu

        @ray_tpu.remote
        class Coordinator:
            def __init__(self):
                self._self_handle = None

            def register(self, handle):
                self._self_handle = Coordinator.remote()

            def run(self, x):
                ref = self._self_handle.helper.remote(x)
                return ray_tpu.get(ref)  # queues behind run() itself

            def helper(self, x):
                return x + 1
    """
    good_example = """
        import ray_tpu

        @ray_tpu.remote
        class Coordinator:
            def helper(self, x):
                return x + 1

            def run(self, x):
                return self.helper(x)  # plain call, same process
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        if module.project is None:
            return []
        out: List[Finding] = []
        seen_nodes: set = set()  # a shared helper flags once, not per actor
        for edge in _actor_edges(module.project):
            if edge.module is not module or edge.src != edge.dst:
                continue
            if id(edge.node) in seen_nodes:
                continue
            seen_nodes.add(id(edge.node))
            out.append(
                self.finding(
                    module,
                    edge.node,
                    f"blocking ray_tpu.get on `{edge.dst_method}` of the "
                    f"same actor class {edge.src[1]} — the task queues "
                    f"behind `{edge.src_method}` and the get never "
                    "returns; await it, return the ref, or call the "
                    "method directly",
                )
            )
        return out


class CrossActorCallCycleRule(Rule):
    id = "RTL702"
    name = "cross-actor-call-cycle"
    family = "actors"
    description = (
        "synchronous cross-actor call cycle (A blocks on B while B "
        "blocks on A) — distributed deadlock"
    )
    rationale = (
        "two single-threaded actors that synchronously ray_tpu.get each "
        "other's tasks deadlock the moment the calls overlap: each actor "
        "is busy waiting, so neither can serve the other's request. The "
        "cycle is detected on the blocking-call graph between actor "
        "classes (strongly-connected components); break it by making one "
        "leg async, returning refs instead of resolving them, or "
        "restructuring so dependencies flow one way."
    )
    bad_example = """
        import ray_tpu

        @ray_tpu.remote
        class Alpha:
            def __init__(self, beta):
                self._beta = Beta.remote()

            def ping(self, x):
                return ray_tpu.get(self._beta.pong.remote(x))

            def poke(self, x):
                return x

        @ray_tpu.remote
        class Beta:
            def __init__(self):
                self._alpha = Alpha.remote(None)

            def pong(self, x):
                return ray_tpu.get(self._alpha.poke.remote(x))
    """
    good_example = """
        import ray_tpu

        @ray_tpu.remote
        class Alpha:
            def __init__(self):
                self._beta = Beta.remote()

            def ping(self, x):
                return ray_tpu.get(self._beta.pong.remote(x))

        @ray_tpu.remote
        class Beta:
            def pong(self, x):
                return x + 1  # leaf actor: dependencies flow one way
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        if module.project is None:
            return []
        edges = _actor_edges(module.project)
        graph: Dict[Tuple, Set[Tuple]] = {}
        for e in edges:
            if e.src != e.dst:  # self-cycles are RTL701's
                graph.setdefault(e.src, set()).add(e.dst)
        comp_of: Dict[Tuple, int] = {}
        for i, comp in enumerate(_sccs(graph)):
            if len(comp) > 1:
                for node in comp:
                    comp_of[node] = i
        out: List[Finding] = []
        seen_nodes: set = set()  # a shared helper flags once, not per actor
        for e in edges:
            if e.module is not module or e.src == e.dst:
                continue
            if id(e.node) in seen_nodes:
                continue
            if e.src in comp_of and comp_of.get(e.dst) == comp_of[e.src]:
                seen_nodes.add(id(e.node))
                out.append(
                    self.finding(
                        module,
                        e.node,
                        f"synchronous call cycle between actor classes "
                        f"{e.src[1]} and {e.dst[1]}: `{e.src_method}` "
                        f"blocks on `{e.dst_method}` while the reverse "
                        "leg blocks back — overlapping calls deadlock "
                        "both actors; make one leg async or return the "
                        "ref",
                    )
                )
        return out


RULES = [SameActorBlockingGetRule, CrossActorCallCycleRule]
