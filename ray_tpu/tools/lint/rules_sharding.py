"""Family 6 — sharding-consistency rules.

RTL601: a `shard_map` (or `NamedSharding`) whose PartitionSpecs name an
axis the mesh at the call site does not have. jax raises at trace time
in the lucky case; with `check_vma=False` (this repo's default through
the compat shim) a misspelled axis can silently mean "replicated",
producing wrong-but-plausible numerics at mesh scale. The mesh's axis
names resolve statically through the project symbol table: a literal
`Mesh(devs, ("dp", "tp"))`, a constant tuple imported from another
module (`AXIS_ORDER` in ray_tpu/parallel/mesh.py), or a helper whose
return is one of those — `MeshSpec(...).build()` included.

RTL602: a collective (`lax.psum`, `ppermute`, `all_gather`,
`axis_index`, ...) inside a shard_map/pmap body naming an axis the
enclosing context does not bind. An unbound axis name is a trace-time
NameError at best; at worst (axis bound by an OUTER map in some call
paths only) a collective quietly reduces over the wrong group. Both
rules resolve the wrapped function through `_resolve_function` across
modules (the `ray_tpu/parallel` + `_private/jax_compat` shims look like
plain calls at the use site).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ray_tpu.tools.lint.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_kwargs,
    resolve_function_ex,
    resolve_name_binding,
)

SHARD_WRAPPER_LASTS = ("shard_map", "pmap")

# collective name -> positional index of its axis-name argument
COLLECTIVE_AXIS_ARG = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "ppermute": 1,
    "all_gather": 1,
    "psum_scatter": 1,
    "all_to_all": 1,
    "axis_index": 0,
    "axis_size": 0,
}


def _is_shard_wrapper(module: ModuleInfo, func: ast.AST) -> Optional[str]:
    dotted = module.dotted_name(func)
    if dotted is None:
        return None
    last = dotted.rsplit(".", 1)[-1]
    return dotted if last in SHARD_WRAPPER_LASTS else None


def shard_sites(module: ModuleInfo) -> List[dict]:
    """Every shard_map/pmap application in the module, normalized:
    {node, desc, fn_expr, kwargs, at} — from direct calls
    (`shard_map(f, mesh=..., in_specs=...)`), partial-decorator form
    (`@partial(shard_map, mesh=..., ...)` on a def), and plain-decorator
    pmap. Memoized per module."""
    cached = module.memo.get("shard_sites")
    if cached is not None:
        return cached
    sites: List[dict] = []
    for node in module.nodes(ast.Call):
        desc = _is_shard_wrapper(module, node.func)
        if desc is None:
            continue
        fn_expr = node.args[0] if node.args else None
        kwargs = call_kwargs(node)
        if fn_expr is None:
            fn_expr = kwargs.get("f") or kwargs.get("fun")
        sites.append(
            dict(node=node, desc=desc, fn_expr=fn_expr, kwargs=kwargs,
                 at=node, fn=None)
        )
    for node in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                desc = _is_shard_wrapper(module, dec)
                if desc is not None:
                    sites.append(
                        dict(node=dec, desc=desc, fn_expr=None, kwargs={},
                             at=node, fn=node)
                    )
                continue
            desc = _is_shard_wrapper(module, dec.func)
            if desc is not None:
                sites.append(
                    dict(node=dec, desc=desc, fn_expr=None,
                         kwargs=call_kwargs(dec), at=node, fn=node)
                )
                continue
            dotted = module.dotted_name(dec.func) or ""
            if dotted.rsplit(".", 1)[-1] == "partial" and dec.args:
                desc = _is_shard_wrapper(module, dec.args[0])
                if desc is not None:
                    sites.append(
                        dict(node=dec, desc=desc, fn_expr=None,
                             kwargs=call_kwargs(dec), at=node, fn=node)
                    )
    module.memo["shard_sites"] = sites
    return sites


def collect_spec_axes(
    module: ModuleInfo, expr: Optional[ast.AST], at: ast.AST
) -> Tuple[Set[str], bool]:
    """Axis names appearing in a PartitionSpec expression (resolving a
    top-level name to its binding first). Returns (axes, fully_known) —
    fully_known is False when any spec component could not be resolved
    to a string, so a caller must not treat the set as exhaustive."""
    if expr is None:
        return (set(), True)
    if isinstance(expr, ast.Name):
        bind = resolve_name_binding(module, expr.id, at)
        if isinstance(bind, ast.Assign):
            expr = bind.value
            at = bind
        else:
            return (set(), False)
    axes: Set[str] = set()
    known = True
    project = module.project
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        dotted = module.dotted_name(node.func)
        if dotted is None:
            continue
        if dotted.rsplit(".", 1)[-1] not in ("P", "PartitionSpec"):
            continue
        for arg in node.args:
            value = (
                project.resolve_constant(module, arg, at)
                if project is not None
                else None
            )
            if value is None and isinstance(arg, ast.Constant):
                value = arg.value
            if value is None:
                if not (
                    isinstance(arg, ast.Constant) and arg.value is None
                ):
                    known = False
                continue
            for axis in value if isinstance(value, tuple) else (value,):
                if isinstance(axis, str):
                    axes.add(axis)
                elif axis is not None:
                    known = False
    return (axes, known)


def resolve_mesh_axes(
    module: ModuleInfo,
    expr: Optional[ast.AST],
    at: ast.AST,
    _depth: int = 0,
) -> Optional[Tuple[str, ...]]:
    """Statically-known axis names of a mesh expression, or None.

    Handles: a literal `Mesh(devs, ("dp", "tp"))` (axes tuple possibly a
    cross-module constant like AXIS_ORDER), a name bound to one, a call
    to a helper function whose return is one (resolved across modules),
    and `Spec(...).build()` where build's return constructs the Mesh."""
    if expr is None or _depth > 6:
        return None
    project = module.project
    if isinstance(expr, ast.Name):
        bind = resolve_name_binding(module, expr.id, at)
        if isinstance(bind, ast.Assign):
            return resolve_mesh_axes(module, bind.value, bind, _depth + 1)
        return None
    if not isinstance(expr, ast.Call):
        return None
    dotted = module.dotted_name(expr.func)
    if dotted is not None and dotted.rsplit(".", 1)[-1] == "Mesh":
        axes_expr = None
        if len(expr.args) >= 2:
            axes_expr = expr.args[1]
        for kw in expr.keywords:
            if kw.arg == "axis_names":
                axes_expr = kw.value
        if axes_expr is None or project is None:
            return None
        value = project.resolve_constant(module, axes_expr, expr)
        if isinstance(value, str):
            return (value,)
        if isinstance(value, tuple) and all(
            isinstance(v, str) for v in value
        ):
            return value
        return None
    # `receiver.build()` — resolve the receiver's class, then analyze its
    # build method's returns.
    if (
        isinstance(expr.func, ast.Attribute)
        and project is not None
    ):
        recv = expr.func.value
        cls = None
        if isinstance(recv, ast.Call):
            sym = project.resolve_expr(module, recv.func)
            if sym is not None and isinstance(sym.node, ast.ClassDef):
                cls = (sym.module, sym.node)
        elif isinstance(recv, (ast.Name, ast.Attribute)):
            if isinstance(recv, ast.Name):
                bind = resolve_name_binding(module, recv.id, at)
                if isinstance(bind, ast.Assign) and isinstance(
                    bind.value, ast.Call
                ):
                    sym = project.resolve_expr(module, bind.value.func)
                    if sym is not None and isinstance(
                        sym.node, ast.ClassDef
                    ):
                        cls = (sym.module, sym.node)
        if cls is not None:
            clsmod, clsnode = cls
            for member in clsnode.body:
                if isinstance(
                    member, ast.FunctionDef
                ) and member.name == expr.func.attr:
                    return _axes_from_returns(clsmod, member, _depth)
        return None
    # Plain helper call, possibly defined in another module.
    resolved = resolve_function_ex(module, expr.func, expr)
    if resolved is not None:
        def_module, fn = resolved
        if not isinstance(fn, ast.Lambda):
            return _axes_from_returns(def_module, fn, _depth)
    return None


def _axes_from_returns(
    module: ModuleInfo, fn: ast.AST, _depth: int
) -> Optional[Tuple[str, ...]]:
    found: Optional[Tuple[str, ...]] = None
    for node in ast.walk(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        axes = resolve_mesh_axes(module, node.value, node, _depth + 1)
        if axes is None:
            continue
        if found is not None and found != axes:
            return None  # ambiguous
        found = axes
    return found


class SpecAxisNotInMeshRule(Rule):
    id = "RTL601"
    name = "spec-axis-not-in-mesh"
    family = "sharding"
    description = (
        "shard_map/NamedSharding PartitionSpec names an axis the mesh at "
        "the call site does not define"
    )
    rationale = (
        "a PartitionSpec axis that isn't in the mesh raises at trace "
        "time at best; with replication checks off (check_vma=False, the "
        "repo default through the compat shim) a typo like 'modle' can "
        "silently mean replicated — numerically wrong at mesh scale with "
        "no error. Mesh axes are resolved statically (literal tuples, "
        "cross-module constants, Spec(...).build() helpers) and the rule "
        "only fires on proven mismatches."
    )
    bad_example = """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from ray_tpu._private.jax_compat import shard_map

        def run(fn, x, devs):
            mesh = Mesh(devs, ("dp", "tp"))
            f = shard_map(fn, mesh=mesh, in_specs=(P("model"),),
                          out_specs=P("dp"))
            return f(x)
    """
    good_example = """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from ray_tpu._private.jax_compat import shard_map

        def run(fn, x, devs):
            mesh = Mesh(devs, ("dp", "tp"))
            f = shard_map(fn, mesh=mesh, in_specs=(P("tp"),),
                          out_specs=P("dp"))
            return f(x)
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for site in shard_sites(module):
            kwargs = site["kwargs"]
            mesh_axes = resolve_mesh_axes(
                module, kwargs.get("mesh"), site["at"]
            )
            if mesh_axes is None:
                continue
            spec_axes: Set[str] = set()
            for key in ("in_specs", "out_specs"):
                axes, _ = collect_spec_axes(
                    module, kwargs.get(key), site["at"]
                )
                spec_axes |= axes
            for axis in sorted(spec_axes - set(mesh_axes)):
                out.append(
                    self.finding(
                        module,
                        site["node"],
                        f"{site['desc']} spec names axis {axis!r} but the "
                        f"mesh at this call site has axes {mesh_axes}; a "
                        "misspelled axis silently means 'replicated' "
                        "under check_vma=False",
                    )
                )
        # NamedSharding(mesh, P(...)) sites get the same treatment.
        for call in module.nodes(ast.Call):
            dotted = module.dotted_name(call.func)
            if dotted is None or (
                dotted.rsplit(".", 1)[-1] != "NamedSharding"
            ):
                continue
            if not call.args:
                continue
            mesh_axes = resolve_mesh_axes(module, call.args[0], call)
            if mesh_axes is None:
                continue
            spec_expr = call.args[1] if len(call.args) > 1 else None
            axes, _ = collect_spec_axes(module, spec_expr, call)
            for axis in sorted(axes - set(mesh_axes)):
                out.append(
                    self.finding(
                        module,
                        call,
                        f"NamedSharding spec names axis {axis!r} but its "
                        f"mesh has axes {mesh_axes}",
                    )
                )
        return out


class CollectiveAxisUnboundRule(Rule):
    id = "RTL602"
    name = "collective-axis-unbound"
    family = "sharding"
    description = (
        "collective inside a shard_map/pmap body names an axis the "
        "enclosing context does not bind"
    )
    rationale = (
        "lax.psum('x') inside a shard_map whose mesh binds only ('dp', "
        "'tp') is a NameError at trace time — or, when an outer map "
        "happens to bind 'x' on SOME call paths, a collective over the "
        "wrong device group: gradients averaged across the wrong "
        "replicas. shard_map binds ALL mesh axes (the specs are only a "
        "subset), so the rule fires only when the mesh's axis set is "
        "statically resolvable and stays silent otherwise."
    )
    bad_example = """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from ray_tpu._private.jax_compat import shard_map

        def grad_sync(x):
            return jax.lax.pmean(x, "dp")

        def run(x, devs):
            mesh = Mesh(devs, ("data", "tp"))
            f = shard_map(grad_sync, mesh=mesh, in_specs=(P("data"),),
                          out_specs=P("data"))
            return f(x)
    """
    good_example = """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from ray_tpu._private.jax_compat import shard_map

        def grad_sync(x):
            return jax.lax.pmean(x, "data")

        def run(x, devs):
            mesh = Mesh(devs, ("data", "tp"))
            f = shard_map(grad_sync, mesh=mesh, in_specs=(P("data"),),
                          out_specs=P("data"))
            return f(x)
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        sites = shard_sites(module)
        # A nested shard_map body checks against ITS axes, not the outer
        # site's — skip resolved inner bodies while walking an outer one.
        resolved: List[Tuple[dict, ModuleInfo, ast.AST]] = []
        for site in sites:
            if site["fn"] is not None:
                resolved.append((site, module, site["fn"]))
                continue
            r = (
                resolve_function_ex(module, site["fn_expr"], site["at"])
                if site["fn_expr"] is not None
                else None
            )
            if r is not None:
                resolved.append((site, r[0], r[1]))
        inner_fns = {id(fn) for _, _, fn in resolved}
        for site, def_module, fn in resolved:
            bound = self._bound_axes(module, site)
            if bound is None:
                continue
            for node in self._body_nodes(fn, inner_fns):
                hit = self._unbound_collective(def_module, node, bound)
                if hit is not None:
                    name, axis = hit
                    out.append(
                        self.finding(
                            def_module,
                            node,
                            f"{name} names axis {axis!r} but the "
                            f"enclosing {site['desc']} binds "
                            f"{tuple(sorted(bound))}; the collective "
                            "would trace-fail or reduce over the wrong "
                            "group",
                        )
                    )
        return out

    def _bound_axes(
        self, module: ModuleInfo, site: dict
    ) -> Optional[Set[str]]:
        """shard_map binds ALL of its mesh's axes in the body — the
        call's PartitionSpecs are only a SUBSET, so an unresolvable mesh
        means the bound set is unknowable and the rule must stay silent
        (a psum over a mesh axis the specs never name is legal and
        common: replicated input, collective over the idle axis)."""
        kwargs = site["kwargs"]
        mesh_axes = resolve_mesh_axes(
            module, kwargs.get("mesh"), site["at"]
        )
        if mesh_axes is not None:
            return set(mesh_axes)
        if site["desc"].rsplit(".", 1)[-1] == "pmap":
            axis_kw = kwargs.get("axis_name")
            if isinstance(axis_kw, ast.Constant) and isinstance(
                axis_kw.value, str
            ):
                return {axis_kw.value}
        return None

    @staticmethod
    def _body_nodes(fn: ast.AST, inner_fns: Set[int]):
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if id(node) in inner_fns and node is not fn:
                continue  # another shard site's body: its own axes apply
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _unbound_collective(
        self, module: ModuleInfo, node: ast.AST, bound: Set[str]
    ) -> Optional[Tuple[str, str]]:
        if not isinstance(node, ast.Call):
            return None
        dotted = module.dotted_name(node.func)
        if dotted is None:
            return None
        last = dotted.rsplit(".", 1)[-1]
        if last not in COLLECTIVE_AXIS_ARG:
            return None
        if "lax" not in dotted and "jax_compat" not in dotted:
            return None
        axis_expr = None
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                axis_expr = kw.value
        if axis_expr is None:
            idx = COLLECTIVE_AXIS_ARG[last]
            if idx < len(node.args):
                axis_expr = node.args[idx]
        if axis_expr is None:
            return None
        value = None
        if isinstance(axis_expr, ast.Constant):
            value = axis_expr.value
        elif module.project is not None:
            value = module.project.resolve_constant(
                module, axis_expr, node
            )
        if value is None:
            return None
        axes = value if isinstance(value, tuple) else (value,)
        for axis in axes:
            if isinstance(axis, str) and axis not in bound:
                return (f"{dotted}()", axis)
        return None


RULES = [SpecAxisNotInMeshRule, CollectiveAxisUnboundRule]
