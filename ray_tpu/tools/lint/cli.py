"""`ray-tpu lint` — CLI for the codebase-aware static analyzer.

    ray-tpu lint [paths ...] [--rule ID] [--json] [--sarif]
                 [--baseline FILE] [--write-baseline] [--list-rules]
                 [--no-baseline] [--explain RULE] [--changed]

Exit codes: 0 — clean (every finding fixed, suppressed with a reason, or
baselined with a reason); 1 — active findings (or untriaged baseline
entries); 2 — usage/parse errors.

`--json` emits a machine-readable report (consumed by the dashboard and
tests). `version` is the SCHEMA version — bumped to 3 with the
diff-scoped scan (`files_checked` key; new keys never appear under an
old version number, so consumers can gate on it):

    {
      "version": 3,
      "schema": "ray-tpu-lint-report/3",
      "root": "/abs/repo",
      "paths": ["ray_tpu"],
      "files_scanned": 240,
      "files_checked": 240,
      "duration_s": 1.8,
      "counts": {"active": 0, "baselined": 12, "suppressed": 4,
                 "parse_errors": 0, "stale_baseline": 0,
                 "untriaged_baseline": 0},
      "findings": [ {rule, name, family, path, line, col, context,
                     message, fingerprint}, ... ],
      "parse_errors": [ {...}, ... ],
      "baselined": [ {... , "reason": "..."}, ... ],
      "suppressed": [ {... , "reason": "..."}, ... ]
    }

`counts.active == len(findings)` always; unparseable files are reported
in their own `parse_errors` array (counted by `counts.parse_errors`).

`--sarif` emits SARIF 2.1.0 for CI annotation pipelines (GitHub code
scanning et al.): active findings as `warning` results, parse errors as
`error`, rule metadata (description + rationale) in the tool driver, and
the lint fingerprint under `partialFingerprints` so annotation dedup
survives line drift. Exit codes match the other modes.

`--explain RULE` prints the rule's rationale plus a minimal bad/good
example pair — the SAME snippets the fixture tests run, so the examples
can never drift from what the rule flags.

`--changed` scopes the scan to the files changed vs git HEAD (tracked
modifications plus untracked .py files) AND their reverse import
dependents from the project model — everything is still parsed so the
cross-module symbol table sees the whole tree, but rules run only on
the diff closure. That is the pre-commit loop: `make lint-changed`.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import List, Optional, Set

from ray_tpu.tools.lint import baseline as baseline_mod
from ray_tpu.tools.lint.core import (
    all_rules,
    find_repo_root,
    lint_paths,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="ray-tpu lint",
        description=(
            "Codebase-aware static analyzer: actor races, async "
            "deadlocks, JIT trace-safety, resource hygiene, buffer "
            "donation, retrace storms, sharding consistency, actor "
            "call-graph deadlocks"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["ray_tpu"],
        help="files or directories to scan (default: ray_tpu)",
    )
    parser.add_argument(
        "--rule", action="append", default=None, metavar="ID",
        help="run only this rule id/name (repeatable)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline file (default: LINT_BASELINE.json at the repo root)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (report everything)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help=(
            "write current active findings into the baseline with TODO "
            "reasons (replace them before committing)"
        ),
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--sarif", action="store_true",
        help="SARIF 2.1.0 output (CI annotations / external tooling)",
    )
    parser.add_argument(
        "--explain", default=None, metavar="RULE",
        help="print a rule's rationale + minimal bad/good example",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help=(
            "check only files changed vs git HEAD plus their reverse "
            "import dependents (the whole tree is still parsed for the "
            "cross-module pass)"
        ),
    )
    return parser


def _git_changed_files(root: Path) -> Optional[Set[str]]:
    """LINT-root-relative posix paths of changed .py files: tracked
    changes vs HEAD plus untracked (not ignored) files. None when git
    is unavailable or `root` is not inside a work tree. `--relative`
    matters: the lint root (pyproject.toml) may be a SUBDIRECTORY of
    the git toplevel, and module relpaths are computed against the lint
    root — without it, diff paths come back toplevel-relative, nothing
    matches, and a monorepo pre-commit run would silently check zero
    files."""
    out: Set[str] = set()
    for cmd in (
        ["git", "-C", str(root), "diff", "--name-only", "--relative",
         "HEAD"],
        ["git", "-C", str(root), "ls-files", "--others",
         "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, check=True
            )
        except (OSError, subprocess.CalledProcessError):
            return None
        out.update(
            line.strip() for line in proc.stdout.splitlines()
            if line.strip().endswith(".py")
        )
    return out


SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _sarif_result(finding, level: str) -> dict:
    return {
        "ruleId": finding.rule,
        "level": level,
        "message": {"text": f"{finding.message} ({finding.context})"},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": max(finding.line, 1),
                        "startColumn": finding.col + 1,
                    },
                }
            }
        ],
        # The lint fingerprint hashes rule+file+scope+normalized source,
        # so CI annotation dedup survives line drift exactly like the
        # checked-in baseline does.
        "partialFingerprints": {
            "rayTpuLint/v1": finding.fingerprint or "",
        },
    }


def sarif_report(result, root: Path) -> dict:
    rules_meta = [
        {
            "id": rule.id,
            "name": rule.name,
            "shortDescription": {"text": rule.description},
            "fullDescription": {
                "text": rule.rationale or rule.description
            },
            "properties": {"family": rule.family},
        }
        for rule in all_rules()
    ]
    results = [_sarif_result(f, "warning") for f in result.findings]
    results.extend(
        _sarif_result(f, "error") for f in result.parse_errors
    )
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "ray-tpu-lint",
                        "informationUri": (
                            "https://github.com/ray-tpu/ray-tpu"
                        ),
                        "rules": rules_meta,
                    }
                },
                "originalUriBaseIds": {
                    "SRCROOT": {"uri": root.resolve().as_uri() + "/"}
                },
                "results": results,
            }
        ],
    }


def explain_rule(rule_id: str) -> int:
    import textwrap

    for rule in all_rules():
        if rule.id != rule_id and rule.name != rule_id:
            continue
        print(f"{rule.id}  {rule.name}  [{rule.family}]")
        print(f"\n{rule.description}\n")
        if rule.rationale:
            print("Why:")
            print(textwrap.fill(rule.rationale, width=72,
                                initial_indent="  ",
                                subsequent_indent="  "))
        if rule.bad_example:
            print("\nFires on:\n")
            print(textwrap.indent(
                textwrap.dedent(rule.bad_example).strip(), "    "))
        if rule.good_example:
            print("\nClean form:\n")
            print(textwrap.indent(
                textwrap.dedent(rule.good_example).strip(), "    "))
        return 0
    print(f"ray-tpu lint: no such rule: {rule_id}", file=sys.stderr)
    return 2


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:24s} [{rule.family}] "
                  f"{rule.description}")
        return 0

    if args.explain:
        return explain_rule(args.explain)

    paths = [Path(p) for p in args.paths]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"ray-tpu lint: no such path: {missing[0]}", file=sys.stderr)
        return 2
    root = find_repo_root(paths[0])
    baseline_path = (
        Path(args.baseline)
        if args.baseline
        else root / baseline_mod.BASELINE_FILENAME
    )
    baseline = (
        {} if args.no_baseline else baseline_mod.load_baseline(baseline_path)
    )

    changed: Optional[Set[str]] = None
    if args.changed:
        changed = _git_changed_files(root)
        if changed is None:
            print(
                "ray-tpu lint: --changed needs a git work tree at "
                f"{root}", file=sys.stderr,
            )
            return 2

    result = lint_paths(
        paths, rule_ids=args.rule, baseline=baseline, root=root,
        changed_only=changed,
    )

    if args.write_baseline:
        # Start from the file on disk, not the (possibly --no-baseline'd
        # or filtered) view used for the scan: entries outside this run's
        # scope must survive, and already-written reasons must never be
        # re-stamped with TODO.
        existing = baseline_mod.load_baseline(baseline_path)
        for f, _ in result.baselined:
            if f.fingerprint in existing:
                existing[f.fingerprint]["line"] = f.line
        new = 0
        for f in result.findings:
            prior = existing.get(f.fingerprint)
            if prior is not None:
                prior["line"] = f.line
            else:
                existing[f.fingerprint] = baseline_mod.entry_for(f)
                new += 1
        # Drop stale entries (the finding no longer exists) — but ONLY
        # those this run could have re-produced: an entry is in scope
        # exactly when its rule was in the scanned rule set AND its
        # file was in the CHECKED set (rules actually ran on it). A
        # scan scoped by path, --rule or --changed must not discard the
        # rest of the baseline — a narrowed run re-fingerprints only
        # what it checked, so everything else (other families, other
        # files, their written reasons) survives verbatim. A file that
        # failed to PARSE this run produced no findings at all, so its
        # triaged entries survive too.
        produced = {f.fingerprint for f in result.findings} | {
            f.fingerprint for f, _ in result.baselined
        }
        parse_failed = {f.path for f in result.parse_errors}
        wanted = set(args.rule) if args.rule else None
        scanned_rules = {
            r.id for r in all_rules()
            if wanted is None or r.id in wanted or r.name in wanted
        }
        # The meta findings are produced outside the registry: RTL002
        # on every run, RTL003 only on full-registry runs — their stale
        # entries are droppable exactly then.
        scanned_rules.add("RTL002")
        if wanted is None:
            scanned_rules.add("RTL003")

        def in_scope(entry: dict) -> bool:
            return (
                entry.get("rule") in scanned_rules
                and entry.get("path") in result.checked_relpaths
            )

        entries = [
            e for fp, e in existing.items()
            if fp in produced
            or e["path"] in parse_failed
            or not in_scope(e)
        ]
        baseline_mod.save_baseline(baseline_path, entries)
        print(
            f"wrote {len(entries)} entries to {baseline_path} "
            f"({new} new with TODO reasons)"
        )
        return 0

    untriaged = baseline_mod.untriaged(
        {
            f.fingerprint: baseline[f.fingerprint]
            for f, _ in result.baselined
            if f.fingerprint in baseline
        }
    )

    if args.sarif:
        print(json.dumps(sarif_report(result, root), indent=2))
    elif args.json:
        report = {
            "version": 3,
            "schema": "ray-tpu-lint-report/3",
            "root": str(root),
            "paths": [str(p) for p in paths],
            "files_scanned": result.files_scanned,
            "files_checked": len(result.checked_relpaths),
            "duration_s": round(result.duration_s, 3),
            "counts": {
                "active": len(result.findings),
                "baselined": len(result.baselined),
                "suppressed": len(result.suppressed),
                "parse_errors": len(result.parse_errors),
                "stale_baseline": len(result.stale_baseline),
                "untriaged_baseline": len(untriaged),
            },
            "findings": [f.to_dict() for f in result.findings],
            "parse_errors": [f.to_dict() for f in result.parse_errors],
            "baselined": [
                {**f.to_dict(), "reason": reason}
                for f, reason in result.baselined
            ],
            "suppressed": [
                {**f.to_dict(), "reason": reason}
                for f, reason in result.suppressed
            ],
        }
        print(json.dumps(report, indent=2))
    else:
        for f in result.parse_errors + result.findings:
            print(
                f"{f.path}:{f.line}:{f.col}: {f.rule} {f.name} "
                f"[{f.family}] {f.message} ({f.context})"
            )
        for entry in untriaged:
            print(
                f"{entry['path']}:{entry.get('line', 0)}: {entry['rule']} "
                f"baseline entry has no written reason ({entry['reason']!r})"
            )
        scope = (
            f"{len(result.checked_relpaths)} changed(+dependents) of "
            f"{result.files_scanned} files"
            if args.changed
            else f"{result.files_scanned} files"
        )
        summary = (
            f"{len(result.findings)} finding(s), "
            f"{len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed, "
            f"{len(result.parse_errors)} parse error(s) in "
            f"{scope} "
            f"({result.duration_s:.2f}s)"
        )
        if result.stale_baseline:
            summary += (
                f"; {len(result.stale_baseline)} stale baseline entr"
                f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
                "(regenerate with --write-baseline)"
            )
        print(summary)

    # Stale entries fail too: the CI gate rejects them, so a local run
    # must not report clean and then break in CI.
    if (
        result.findings
        or result.parse_errors
        or untriaged
        or result.stale_baseline
    ):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
