"""Family 1 — actor/async deadlock rules.

RTL101: blocking calls inside `async def`. A coroutine runs on the actor's
single event loop; one blocking `ray_tpu.get()` / `Future.result()` /
`time.sleep()` stalls EVERY in-flight request on that actor, and when the
awaited result depends on another task of the same actor it deadlocks
outright. Calls shipped off-loop (`run_in_executor`, `asyncio.to_thread`,
thread/executor submission) are exempt, as is anything directly awaited.

RTL102: `await` while holding a `threading.Lock`/`RLock`/`Condition`. The
suspended coroutine keeps the OS lock; any thread (or any coroutine on
this loop that needs the same lock before the holder resumes) blocks the
whole loop — the classic async-deadlock.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from ray_tpu.tools.lint.core import Finding, ModuleInfo, Rule
from ray_tpu.tools.lint.rules_locks import class_lock_attrs, is_lock_ctor

# Dotted call targets that block the calling thread.
BLOCKING_CALLS = {
    "time.sleep",
    "ray_tpu.get",
    "ray_tpu.wait",
    "ray_tpu.api.get",
    "ray_tpu.api.wait",
}

# Ship-it-off-loop wrappers: a blocking call lexically inside one of
# these is the sanctioned pattern, not a finding.
OFFLOAD_CALLS = {"run_in_executor", "to_thread", "submit", "start"}

BLOCKING_METHODS = {"result"}  # concurrent.futures.Future.result()


def _enclosing_async_def(module: ModuleInfo, node: ast.AST):
    cur = module.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.Lambda)):
            return None  # nested sync def: runs wherever it's called
        if isinstance(cur, ast.AsyncFunctionDef):
            return cur
        cur = module.parent(cur)
    return None


def _is_offloaded(module: ModuleInfo, node: ast.AST, stop: ast.AST) -> bool:
    cur = module.parent(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.Call):
            func = cur.func
            if isinstance(func, ast.Attribute) and func.attr in OFFLOAD_CALLS:
                return True
        cur = module.parent(cur)
    return False


class AsyncBlockingCallRule(Rule):
    id = "RTL101"
    name = "async-blocking-call"
    family = "async"
    description = (
        "blocking call (ray_tpu.get / Future.result / time.sleep / "
        "lock.acquire / Event.wait) inside async def stalls the event loop"
    )
    rationale = (
        "a coroutine runs on the actor's single event loop; one blocking "
        "call stalls EVERY in-flight request on that actor, and when the "
        "awaited result depends on another task of the same actor it "
        "deadlocks outright. Ship blocking work off-loop with "
        "run_in_executor/to_thread, or use the async variant."
    )
    bad_example = """
        import ray_tpu

        async def handler(ref):
            return ray_tpu.get(ref)
    """
    good_example = """
        import asyncio

        async def handler(loop, ref):
            return await loop.run_in_executor(None, fetch, ref)
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for node in module.nodes(ast.Call):
            owner = _enclosing_async_def(module, node)
            if owner is None:
                continue
            if isinstance(module.parent(node), ast.Await):
                continue
            label = self._blocking_label(module, node)
            if label is None:
                continue
            if _is_offloaded(module, node, owner):
                continue
            out.append(
                self.finding(
                    module,
                    node,
                    f"blocking {label} inside `async def {owner.name}` "
                    "stalls the actor's event loop (use the async variant "
                    "or run_in_executor)",
                )
            )
        return out

    def _blocking_label(self, module: ModuleInfo, call: ast.Call):
        target = module.call_target(call)
        if target in BLOCKING_CALLS:
            return f"{target}()"
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in BLOCKING_METHODS and len(call.args) <= 1:
                return f".{func.attr}()"
            if func.attr == "wait" and self._receiver_is_threading_sync(
                module, func.value
            ):
                return ".wait() on a threading primitive"
            if func.attr == "acquire" and self._receiver_is_threading_sync(
                module, func.value
            ):
                return ".acquire() on a threading lock"
        return None

    def _receiver_is_threading_sync(self, module, recv: ast.AST) -> bool:
        """True when the receiver is provably a threading Event/Lock:
        a self-attr or local assigned from threading.Event()/Lock()/..."""
        ctors = {
            "threading.Event", "threading.Lock", "threading.RLock",
            "threading.Condition", "threading.Semaphore",
            "threading.Barrier",
        }
        names = module.memo.get("threading_sync_names")
        if names is None:
            names = {}
            for node in module.nodes(ast.Assign):
                if isinstance(node.value, ast.Call) and (
                    module.call_target(node.value) in ctors
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names[("local", t.id)] = True
                        elif (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            names[("attr", t.attr)] = True
            module.memo["threading_sync_names"] = names
        if isinstance(recv, ast.Name):
            return names.get(("local", recv.id), False)
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
        ):
            return names.get(("attr", recv.attr), False)
        return False


class AwaitHoldingLockRule(Rule):
    id = "RTL102"
    name = "await-holding-lock"
    family = "async"
    description = (
        "await while holding a threading lock parks the lock across a "
        "suspension point — any contender deadlocks the loop"
    )
    rationale = (
        "the suspended coroutine keeps the OS lock; any thread — or any "
        "coroutine on this loop that needs the same lock before the "
        "holder resumes — blocks the whole event loop. Use an asyncio "
        "lock, or release before awaiting."
    )
    bad_example = """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            async def bad(self, coro):
                with self._lock:
                    await coro
    """
    good_example = """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            async def good(self, coro):
                with self._lock:
                    pass
                await coro
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for node in module.nodes(ast.AsyncFunctionDef):
            out.extend(self._check_async_fn(module, node))
        return out

    def _check_async_fn(self, module, fn: ast.AsyncFunctionDef):
        cls = self._enclosing_class(module, fn)
        lock_attrs = class_lock_attrs(module, cls) if cls else {}
        local_locks = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and is_lock_ctor(
                module, node.value
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local_locks.add(t.id)

        findings: List[Finding] = []

        def lockish(expr: ast.AST) -> str:
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in lock_attrs
            ):
                return f"self.{expr.attr}"
            if isinstance(expr, ast.Name) and expr.id in local_locks:
                return expr.id
            return ""

        def visit(node, held: str):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    name = lockish(item.context_expr)
                    if name:
                        inner = name
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Await) and held:
                findings.append(
                    self.finding(
                        module,
                        node,
                        f"await while holding threading lock {held} in "
                        f"`async def {fn.name}` — the lock stays held "
                        "across the suspension (deadlock hazard); use an "
                        "asyncio lock or release before awaiting",
                    )
                )
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, "")
        return findings

    def _enclosing_class(self, module, fn):
        cur = module.parent(fn)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = module.parent(cur)
        return None


RULES = [AsyncBlockingCallRule, AwaitHoldingLockRule]
