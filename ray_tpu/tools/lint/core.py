"""Core of `ray-tpu lint`: findings, module model, rule registry, runner.

A rule is a class with an `id` (stable, e.g. "RTL201"), a short `name`,
a `family` (async / locks / trace / resources) and a `check(module)`
returning findings. Rules work on a `ModuleInfo` — one parsed file plus
the derived maps every rule needs (import aliases, AST parent links,
inline suppressions) so each rule stays a focused AST pass.

Suppression idiom (reason is REQUIRED — an unexplained ignore is itself
reported as RTL002):

    do_risky_thing()  # ray-tpu: lint-ignore[RTL201] probe reads a stale
                      # bool at worst; the lock would serialize the loop

A standalone suppression comment applies to the next code line. Findings
neither fixed nor suppressible inline live in the checked-in baseline
(see baseline.py) with a written reason per entry.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

FAMILIES = (
    "meta", "async", "locks", "trace", "resources",
    "donation", "sharding", "actors", "shapes",
)

SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist", "node_modules"}
SKIP_FILE_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")

_SUPPRESS_RE = re.compile(
    r"#\s*ray-tpu:\s*lint-ignore\[([^\]]*)\]\s*(.*)$"
)


@dataclasses.dataclass
class Finding:
    rule: str
    name: str
    family: str
    path: str  # repo-relative posix path
    line: int
    col: int
    context: str  # dotted qualname of the enclosing scope
    message: str
    fingerprint: str = ""

    def key(self) -> tuple:
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _fingerprint(rule: str, path: str, context: str, line_text: str,
                 occurrence: int) -> str:
    # Line NUMBERS drift with every edit; the fingerprint hashes the rule,
    # file, enclosing scope and the normalized source text instead, so a
    # baseline survives unrelated churn above the finding.
    normalized = "".join(line_text.split())
    payload = f"{rule}|{path}|{context}|{normalized}|{occurrence}"
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


class Suppression:
    __slots__ = ("line", "ids", "reason", "used")

    def __init__(self, line: int, ids: set, reason: str):
        self.line = line
        self.ids = ids
        self.reason = reason
        self.used = False

    def matches(self, finding: Finding) -> bool:
        return "*" in self.ids or finding.rule in self.ids or (
            finding.name in self.ids
        )


def _matching_suppression(
    sups: Optional[List[Suppression]], finding: Finding
) -> Optional[Suppression]:
    """First suppression on the finding's line that names its rule AND
    carries a reason. RTL002 (reasonless ignore) is never suppressible."""
    if not sups or finding.rule == "RTL002":
        return None
    for sup in sups:
        if sup.reason and sup.matches(finding):
            return sup
    return None


_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def module_name_for(relpath: str) -> str:
    """Dotted module name of a repo-relative path:
    "ray_tpu/llm/engine.py" -> "ray_tpu.llm.engine",
    "ray_tpu/llm/__init__.py" -> "ray_tpu.llm"."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [part for part in p.split("/") if part]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ModuleInfo:
    """One parsed source file plus the shared derived structure.

    Everything rules repeatedly need is computed in ONE traversal:
    parent links, a by-type node index, and scope ownership (each node
    mapped to its nearest enclosing function/lambda/module), so rules
    never re-walk the whole tree. A per-module memo dict lets rules
    share expensive derived maps (lock attrs, jitted functions)."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # Backref set by ProjectInfo when this module is part of a
        # project-level scan; None for standalone snippets.
        self.project = None
        self.parents: Dict[int, ast.AST] = {}
        self.by_type: Dict[type, List[ast.AST]] = {}
        # scope node (Module/FunctionDef/AsyncFunctionDef/Lambda) id ->
        # nodes owned directly by that scope (not by a nested scope).
        self.scope_nodes: Dict[int, List[ast.AST]] = {id(self.tree): []}
        self.scopes: List[ast.AST] = [self.tree]
        self.memo: Dict[str, object] = {}
        stack = [(self.tree, self.tree)]
        while stack:
            node, scope = stack.pop()
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
                self.by_type.setdefault(type(child), []).append(child)
                child_scope = scope
                if isinstance(child, _SCOPE_TYPES):
                    self.scopes.append(child)
                    self.scope_nodes[id(child)] = []
                    child_scope = child
                else:
                    self.scope_nodes[id(scope)].append(child)
                stack.append((child, child_scope))
        # name -> dotted module ("np" -> "numpy"); from-imports map the
        # bound name to "module.attr" ("jit" -> "jax.jit"). Relative
        # imports resolve against this file's package so a project-level
        # scan can follow `from .engine import X` across files.
        self.aliases: Dict[str, str] = {}
        for node in self.nodes(ast.Import):
            for a in node.names:
                self.aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        for node in self.nodes(ast.ImportFrom):
            base = self._import_base(node)
            if base is None:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                self.aliases[a.asname or a.name] = f"{base}.{a.name}"
        self.suppressions = self._parse_suppressions()
        self._expand_suppressions()

    def _import_base(self, node: ast.ImportFrom) -> Optional[str]:
        """Absolute dotted base of an ImportFrom. `from a.b import c` ->
        "a.b"; `from .sib import c` in pkg/mod.py -> "pkg.sib"; a relative
        import that climbs above the scan root resolves to None."""
        if not node.level:
            return node.module
        pkg_parts = module_name_for(self.relpath).split(".")
        if not self.relpath.endswith("__init__.py"):
            pkg_parts = pkg_parts[:-1]  # plain module: package is the dir
        drop = node.level - 1
        if drop > len(pkg_parts):
            return None
        base_parts = pkg_parts[: len(pkg_parts) - drop]
        if node.module:
            base_parts.append(node.module)
        return ".".join(base_parts) or None

    def nodes(self, *types: type) -> List[ast.AST]:
        if len(types) == 1:
            return self.by_type.get(types[0], [])
        out: List[ast.AST] = []
        for t in types:
            out.extend(self.by_type.get(t, []))
        return out

    def own_nodes(self, scope: ast.AST) -> List[ast.AST]:
        """Nodes owned directly by `scope`, excluding nested functions."""
        return self.scope_nodes.get(id(scope), [])

    # -- suppressions -------------------------------------------------------

    def _parse_suppressions(self) -> Dict[int, List[Suppression]]:
        # A list per line: several standalone lint-ignore comments stacked
        # above one statement all resolve to that statement's line, and
        # each must keep its own ids + reason.
        # Lines inside multi-line string literals are string CONTENT, not
        # comments — a docstring showing the idiom must not register.
        in_string: set = set()
        for node in self.nodes(ast.Constant):
            if (
                isinstance(node.value, str)
                and getattr(node, "end_lineno", node.lineno) > node.lineno
            ):
                in_string.update(range(node.lineno, node.end_lineno + 1))
        out: Dict[int, List[Suppression]] = {}
        for i, text in enumerate(self.lines, start=1):
            if i in in_string:
                continue
            m = _SUPPRESS_RE.search(text)
            if m is None:
                continue
            ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
            reason = m.group(2).strip()
            line = i
            if text.lstrip().startswith("#"):
                # Standalone comment: applies to the next code line.
                j = i + 1
                while j <= len(self.lines) and (
                    not self.lines[j - 1].strip()
                    or self.lines[j - 1].lstrip().startswith("#")
                ):
                    j += 1
                line = j
            out.setdefault(line, []).append(Suppression(line, ids, reason))
        return out

    def _expand_suppressions(self) -> None:
        """Extend each suppression across the statement it anchors to, so
        an ignore above a black-wrapped expression reaches findings whose
        AST node sits on a continuation line. Compound statements extend
        over their HEADER only (`with`/`if`/`def` lines up to the first
        body statement) — an ignore must never blanket a whole block."""
        if not self.suppressions:
            return
        spans: Dict[int, int] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt):
                continue
            body = getattr(node, "body", None)
            if isinstance(body, list) and body and hasattr(body[0], "lineno"):
                end = max(node.lineno, body[0].lineno - 1)
            else:
                end = getattr(node, "end_lineno", None) or node.lineno
            prev = spans.get(node.lineno)
            spans[node.lineno] = end if prev is None else max(prev, end)
        for line, sups in list(self.suppressions.items()):
            for extra in range(line + 1, spans.get(line, line) + 1):
                self.suppressions.setdefault(extra, []).extend(sups)

    def suppression_findings(self) -> List[Finding]:
        """RTL002: a lint-ignore with no written reason is not a valid
        suppression (and does not suppress anything)."""
        out = []
        # Expansion aliases one Suppression onto several lines — report
        # each object once, at its anchor.
        unique = {
            id(s): s for sups in self.suppressions.values() for s in sups
        }
        for sup in unique.values():
            if not sup.reason:
                out.append(
                    Finding(
                        rule="RTL002",
                        name="suppression-missing-reason",
                        family="meta",
                        path=self.relpath,
                        line=sup.line,
                        col=0,
                        context="<module>",
                        message=(
                            "lint-ignore without a reason; write why the "
                            "finding is a false positive after the bracket"
                        ),
                    )
                )
        return out

    # -- resolution helpers -------------------------------------------------

    def dotted_name(self, node: ast.AST) -> Optional[str]:
        """`a.b.c` for an Attribute/Name chain, with the root mapped
        through the module's import aliases. None for dynamic receivers."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def call_target(self, call: ast.Call) -> Optional[str]:
        return self.dotted_name(call.func)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


def qualname_of(module: ModuleInfo, node: ast.AST) -> str:
    """Dotted path of the scopes enclosing `node` (classes + functions)."""
    parts: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            parts.append(cur.name)
        cur = module.parent(cur)
    return ".".join(reversed(parts)) or "<module>"


# -- name/function binding resolution (shared by rule families) -------------


def call_kwargs(call: ast.Call) -> Dict[str, ast.AST]:
    """Named keyword arguments of a call (a `**splat` contributes none)."""
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _target_binds(target: ast.AST, name: str) -> bool:
    """Does an assignment-like target bind `name`? Sees through tuple /
    list unpacking and starred elements."""
    if isinstance(target, ast.Name):
        return target.id == name
    if isinstance(target, (ast.Tuple, ast.List)):
        return any(_target_binds(el, name) for el in target.elts)
    if isinstance(target, ast.Starred):
        return _target_binds(target.value, name)
    return False


def _param_names(fn: ast.AST) -> set:
    a = fn.args
    names = {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
    if a.vararg is not None:
        names.add(a.vararg.arg)
    if a.kwarg is not None:
        names.add(a.kwarg.arg)
    return names


def _scope_level_nodes(scope: ast.AST):
    """Nodes lexically inside `scope` without descending into nested
    scopes — a function/class body introduces its own namespace, so its
    bindings are not visible where `scope`'s are."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
        ):
            stack.extend(ast.iter_child_nodes(node))


def _binding_of(node: ast.AST, name: str) -> Optional[ast.AST]:
    """The node, when it is a statement binding `name` (def, assignment,
    for/with target); else None."""
    if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ) and node.name == name:
        return node
    if isinstance(node, ast.Assign) and any(
        _target_binds(t, name) for t in node.targets
    ):
        return node
    if isinstance(
        node, (ast.AnnAssign, ast.NamedExpr)
    ) and _target_binds(node.target, name):
        return node
    if isinstance(node, (ast.For, ast.AsyncFor)) and _target_binds(
        node.target, name
    ):
        return node
    if isinstance(node, (ast.With, ast.AsyncWith)) and any(
        item.optional_vars is not None
        and _target_binds(item.optional_vars, name)
        for item in node.items
    ):
        return node
    return None


def _bound_names(node: ast.AST) -> List[str]:
    """Names an assignment-like statement binds (see _binding_of)."""
    if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ):
        return [node.name]
    out: List[str] = []

    def collect(target: ast.AST) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name) and isinstance(
                sub.ctx, ast.Store
            ):
                out.append(sub.id)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            collect(t)
    elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)):
        collect(node.target)
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        collect(node.target)
    elif isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    return out


def _module_scope_bindings(module: ModuleInfo) -> Dict[str, ast.AST]:
    """name -> LAST module-level binding. The module scope is scanned by
    every name lookup that escapes a function (the call graph does many
    thousands per scan), so it is memoized once per module; the latest
    binding wins, matching the non-sequential walk."""
    cached = module.memo.get("module_scope_bindings")
    if cached is not None:
        return cached
    out: Dict[str, ast.AST] = {}
    for node in _scope_level_nodes(module.tree):
        for name in _bound_names(node):
            prev = out.get(name)
            if prev is None or node.lineno > prev.lineno:
                out[name] = node
    module.memo["module_scope_bindings"] = out
    return out


def _scope_binding_index(
    module: ModuleInfo, scope: ast.AST
) -> Dict[str, List[ast.AST]]:
    """name -> binding statements at `scope` level, memoized per scope.
    `resolve_name_binding` is on the hot path of the call graph AND the
    RTL8xx abstract interpreter; re-walking a scope's statements per
    lookup was the dominant cost of a full scan."""
    memo = module.memo.setdefault("scope_binding_index", {})
    cached = memo.get(id(scope))
    if cached is not None:
        return cached
    index: Dict[str, List[ast.AST]] = {}
    for node in _scope_level_nodes(scope):
        # _bound_names answers exactly the names _binding_of binds
        # (its docstring points back at the predicate).
        for name in _bound_names(node):
            index.setdefault(name, []).append(node)
    memo[id(scope)] = index
    return index


def resolve_name_binding(
    module: ModuleInfo, name: str, at: ast.AST
) -> Optional[ast.AST]:
    """Latest live binding of a bare name visible at `at`, with the same
    scoping semantics as `_resolve_function` (innermost scope first,
    latest binding not past the use site wins inside the function holding
    `at`, class scope skipped from inside methods, opaque local bindings
    stop the walk). Returns the binding statement (def / Assign / For /
    With), or None."""
    scope = module.parent(at)
    chain = []
    while scope is not None:
        chain.append(scope)
        scope = module.parent(scope)
    if not chain or chain[-1] is not module.tree:
        chain.append(module.tree)
    sequential = True
    crossed_function = False
    for scope in chain:
        if isinstance(scope, ast.ClassDef) and crossed_function:
            continue
        if scope is module.tree and not sequential:
            # Hot path: every lookup that escapes a function lands here —
            # use the memoized module-level map instead of rescanning.
            # (module.tree is always the last scope in the chain, so a
            # miss here is the walk's final None.)
            return _module_scope_bindings(module).get(name)
        best = None
        for node in _scope_binding_index(module, scope).get(name, ()):
            if sequential and (
                node.lineno > getattr(at, "lineno", node.lineno)
            ):
                continue
            if best is None or node.lineno > best.lineno:
                best = node
        if isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            sequential = False
            crossed_function = True
            if best is None and name in _param_names(scope):
                return None  # bound by a parameter: opaque
        if best is not None:
            return best
    return None


def _resolve_function(
    module: ModuleInfo, expr: ast.AST, at: ast.AST, _depth: int = 0
):
    """Map a function expression to a FunctionDef/Lambda defined in this
    module: a bare name (module function or sibling nested def), a
    `self._method`, or an inline lambda. Sees through
    `functools.partial(fn, ...)` — inline, or bound to a local name first
    (`kernel = functools.partial(fn, ...)`), the two ways Pallas kernels
    are handed to pallas_call. None when not resolvable."""
    if _depth > 8:  # self-referential bindings (f = partial(f, ...))
        return None
    if isinstance(expr, ast.Lambda):
        return expr
    if isinstance(expr, ast.Call):
        dotted = module.dotted_name(expr.func)
        if (
            dotted is not None
            and dotted.rsplit(".", 1)[-1] == "partial"
            and expr.args
        ):
            return _resolve_function(module, expr.args[0], at, _depth + 1)
        return None
    if isinstance(expr, ast.Name):
        best = resolve_name_binding(module, expr.id, at)
        if best is None:
            return None
        if isinstance(best, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return best
        # Some assignment-like form binds the name: resolve its value
        # where one maps to the name directly, else give up — walking
        # outward would analyze a shadowed, never-traced binding (tuple
        # unpacking, for/with targets, bare annotations are all opaque).
        if isinstance(best, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == expr.id
            for t in best.targets
        ):
            return _resolve_function(module, best.value, at, _depth + 1)
        if (
            isinstance(best, (ast.AnnAssign, ast.NamedExpr))
            and best.value is not None
        ):
            return _resolve_function(module, best.value, at, _depth + 1)
        return None
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        cls = module.parent(at)
        while cls is not None and not isinstance(cls, ast.ClassDef):
            cls = module.parent(cls)
        if cls is not None:
            for node in cls.body:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and node.name == expr.attr:
                    return node
    return None


def resolve_function_ex(
    module: ModuleInfo, expr: ast.AST, at: ast.AST, _depth: int = 0
) -> Optional[Tuple[ModuleInfo, ast.AST]]:
    """`_resolve_function` extended across module boundaries: when the
    expression names an import (directly, through `as`-alias chains, or
    re-exported by an `__init__.py`), the project symbol table maps it to
    the defining module's FunctionDef. Returns (defining_module, fn)."""
    fn = _resolve_function(module, expr, at)
    if fn is not None:
        return (module, fn)
    project = module.project
    if project is None or _depth > 8:
        return None
    if isinstance(expr, ast.Call):
        dotted = module.dotted_name(expr.func)
        if (
            dotted is not None
            and dotted.rsplit(".", 1)[-1] == "partial"
            and expr.args
        ):
            return resolve_function_ex(module, expr.args[0], at, _depth + 1)
        return None
    dotted = module.dotted_name(expr)
    if dotted is None:
        return None
    sym = project.resolve(dotted)
    if sym is not None and isinstance(
        sym.node, (ast.FunctionDef, ast.AsyncFunctionDef)
    ):
        return (sym.module, sym.node)
    return None


class Rule:
    id = "RTL000"
    name = "abstract"
    family = "meta"
    description = ""
    # `--explain` material: why the rule exists plus a minimal firing /
    # exempt snippet pair. The same snippets double as fixture tests
    # (tests/test_lint.py parametrizes over them), so the CLI's examples
    # can never drift from what the rule actually flags.
    rationale = ""
    bad_example = ""
    good_example = ""

    def check(self, module: ModuleInfo) -> List[Finding]:
        raise NotImplementedError

    def finding(
        self, module: ModuleInfo, node: ast.AST, message: str
    ) -> Finding:
        return Finding(
            rule=self.id,
            name=self.name,
            family=self.family,
            path=module.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            context=qualname_of(module, node),
            message=message,
        )


def all_rules() -> List[Rule]:
    from ray_tpu.tools.lint import (  # noqa: PLC0415 — avoid import cycle
        rules_actors,
        rules_async,
        rules_donation,
        rules_locks,
        rules_resources,
        rules_shapes,
        rules_sharding,
        rules_trace,
    )

    rules: List[Rule] = []
    for mod in (
        rules_async,
        rules_locks,
        rules_trace,
        rules_resources,
        rules_donation,
        rules_sharding,
        rules_actors,
        rules_shapes,
    ):
        rules.extend(r() for r in mod.RULES)
    return rules


def iter_python_files(paths: Sequence[Path]) -> Iterable[Path]:
    # Overlapping scan paths (`lint ray_tpu ray_tpu/_private`) must not
    # yield a file twice: duplicate findings get occurrence-shifted
    # fingerprints that no longer match the baseline.
    seen = set()
    for path in paths:
        if path.is_file():
            if path.suffix == ".py" and path.resolve() not in seen:
                seen.add(path.resolve())
                yield path
            continue
        for sub in sorted(path.rglob("*.py")):
            # Only components BELOW the scan root count: a checkout that
            # happens to live under ~/.cache or a dir named `build` must
            # not make the whole scan vacuously clean.
            if any(part in SKIP_DIRS or part.startswith(".")
                   for part in sub.relative_to(path).parts):
                continue
            if sub.name.endswith(SKIP_FILE_SUFFIXES):
                continue
            resolved = sub.resolve()
            if resolved in seen:
                continue
            seen.add(resolved)
            yield sub


def find_repo_root(start: Path) -> Path:
    """Directory the baseline lives in: nearest ancestor (of the first
    scanned path) holding a pyproject.toml, else the CWD."""
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    for candidate in (cur, *cur.parents):
        if (candidate / "pyproject.toml").exists():
            return candidate
    return Path.cwd()


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # active (not suppressed, not baselined)
    suppressed: List[Tuple[Finding, str]]  # (finding, reason)
    baselined: List[Tuple[Finding, str]]
    parse_errors: List[Finding]
    files_scanned: int
    duration_s: float
    stale_baseline: List[str] = dataclasses.field(default_factory=list)
    # Relpaths rules actually ran on. Equals every parsed file on a full
    # scan; a --changed scan parses everything (the project model needs
    # the whole tree) but checks only the diff closure — and baseline
    # bookkeeping (stale detection, --write-baseline drops) must scope
    # to THIS set, never to everything parsed.
    checked_relpaths: set = dataclasses.field(default_factory=set)


def _unused_suppression_findings(
    suppressions: Dict[int, List[Suppression]], relpath: str
) -> List[Finding]:
    """RTL003: a reasoned lint-ignore whose finding no longer fires is
    rot — the hazard was fixed (delete the comment) or the comment
    drifted off the flagged statement (it no longer protects anything).
    Only meaningful when the FULL rule registry ran: under --rule the
    other rules' suppressions legitimately match nothing."""
    out = []
    unique = {id(s): s for sups in suppressions.values() for s in sups}
    for sup in unique.values():
        if sup.reason and not sup.used:
            out.append(
                Finding(
                    rule="RTL003",
                    name="unused-suppression",
                    family="meta",
                    path=relpath,
                    line=sup.line,
                    col=0,
                    context="<module>",
                    message=(
                        "lint-ignore["
                        + ",".join(sorted(sup.ids))
                        + "] suppresses nothing; delete it or re-anchor "
                        "it to the flagged statement"
                    ),
                )
            )
    return out


def lint_paths(
    paths: Sequence[Path],
    rules: Optional[Sequence[Rule]] = None,
    rule_ids: Optional[Sequence[str]] = None,
    baseline: Optional[dict] = None,
    root: Optional[Path] = None,
    changed_only: Optional[Sequence[str]] = None,
) -> LintResult:
    """Scan `paths`. With `changed_only` (repo-relative posix paths of
    changed files), EVERYTHING is still parsed — the cross-module
    symbol table and call graph must see the whole scan — but rules run
    only on the changed files plus their reverse import dependents from
    the project model (`ray-tpu lint --changed`)."""
    t0 = time.perf_counter()
    full_run = rules is None and not rule_ids
    rules = list(rules) if rules is not None else all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        rules = [r for r in rules if r.id in wanted or r.name in wanted]
    root = root or find_repo_root(Path(paths[0]))
    baseline = baseline or {}

    raw: List[Finding] = []
    parse_errors: List[Finding] = []
    suppressions_by_file: Dict[str, Dict[int, List[Suppression]]] = {}
    lines_by_file: Dict[str, List[str]] = {}
    n_files = 0
    # Two phases: parse EVERYTHING first so the cross-module symbol table
    # / call graph sees the whole scan, then run rules per module (the
    # per-module memoization from the single-pass design still holds; the
    # project adds its own memo for cross-module derived structure).
    modules: List[ModuleInfo] = []
    for file in iter_python_files([Path(p) for p in paths]):
        n_files += 1
        try:
            relpath = file.resolve().relative_to(root).as_posix()
        except ValueError:
            relpath = file.as_posix()
        try:
            module = ModuleInfo(file, relpath, file.read_text())
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            parse_errors.append(
                Finding(
                    rule="RTL001",
                    name="parse-error",
                    family="meta",
                    path=relpath,
                    line=getattr(exc, "lineno", 0) or 0,
                    col=0,
                    context="<module>",
                    message=f"could not parse: {exc}",
                )
            )
            continue
        modules.append(module)

    from ray_tpu.tools.lint.project import ProjectInfo  # noqa: PLC0415

    project = ProjectInfo(modules)
    if changed_only is None:
        checked = {m.relpath for m in modules}
    else:
        checked = project.reverse_import_closure(set(changed_only))
    for module in modules:
        lines_by_file[module.relpath] = module.lines
        # Suppressions classify by the FINDING's path, and a checked
        # module's cross-module rule may attribute a finding to an
        # unchecked defining module — so every parsed module's
        # suppressions stay available, while rules (and the meta
        # suppression findings) run only on the checked set.
        suppressions_by_file[module.relpath] = module.suppressions
        if module.relpath not in checked:
            continue
        raw.extend(module.suppression_findings())
        for rule in rules:
            raw.extend(rule.check(module))

    raw.sort(key=Finding.key)
    # Occurrence-stable fingerprints for findings that normalize to the
    # same source text within one scope.
    seen: Dict[tuple, int] = {}
    for f in raw:
        lines = lines_by_file.get(f.path, [])
        line_text = lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        base = (f.rule, f.path, f.context, "".join(line_text.split()))
        occ = seen.get(base, 0)
        seen[base] = occ + 1
        f.fingerprint = _fingerprint(f.rule, f.path, f.context, line_text, occ)

    active: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    baselined: List[Tuple[Finding, str]] = []
    produced = set()
    for f in raw:
        produced.add(f.fingerprint)
        sup = _matching_suppression(
            suppressions_by_file.get(f.path, {}).get(f.line), f
        )
        if sup is not None:
            sup.used = True
            suppressed.append((f, sup.reason))
            continue
        if f.fingerprint in baseline:
            baselined.append((f, baseline[f.fingerprint].get("reason", "")))
            continue
        active.append(f)

    if full_run:
        # Orphaned suppressions are only knowable after every rule had
        # its chance to match them, so they classify here (baseline
        # honored; inline self-suppression would be circular, skipped).
        orphans: List[Finding] = []
        for relpath, sups in suppressions_by_file.items():
            if relpath not in checked:
                # An unchecked module's suppressions matched nothing
                # because its rules never ran, not because they rotted.
                continue
            orphans.extend(_unused_suppression_findings(sups, relpath))
        orphans.sort(key=Finding.key)
        for f in orphans:
            lines = lines_by_file.get(f.path, [])
            line_text = (
                lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
            )
            base = (f.rule, f.path, f.context, "".join(line_text.split()))
            occ = seen.get(base, 0)
            seen[base] = occ + 1
            f.fingerprint = _fingerprint(
                f.rule, f.path, f.context, line_text, occ
            )
            produced.add(f.fingerprint)
            if f.fingerprint in baseline:
                baselined.append(
                    (f, baseline[f.fingerprint].get("reason", ""))
                )
            else:
                active.append(f)
        active.sort(key=Finding.key)

    # Stale = the scan COULD have re-produced the entry (its file was
    # CHECKED with its rule active) and did not. A path-, rule- or
    # diff-scoped run must not report the rest of the baseline as
    # stale. The meta findings are producible too: RTL002 on every run,
    # RTL003 only when the full registry ran.
    scanned_rule_ids = {r.id for r in rules} | {"RTL002"}
    if full_run:
        scanned_rule_ids.add("RTL003")
    stale = [
        fp for fp, entry in baseline.items()
        if fp not in produced
        and entry.get("rule") in scanned_rule_ids
        and entry.get("path") in checked
    ]
    return LintResult(
        findings=active,
        suppressed=suppressed,
        baselined=baselined,
        parse_errors=parse_errors,
        files_scanned=n_files,
        duration_s=time.perf_counter() - t0,
        stale_baseline=stale,
        checked_relpaths=checked,
    )


def lint_source(
    source: str,
    rules: Optional[Sequence[Rule]] = None,
    relpath: str = "snippet.py",
) -> List[Finding]:
    """Run rules on an in-memory snippet (test harness entry point);
    returns ALL findings, honoring inline suppressions but no baseline."""
    return lint_sources({relpath: source}, rules=rules)


def lint_sources(
    sources: Dict[str, str],
    rules: Optional[Sequence[Rule]] = None,
) -> List[Finding]:
    """Run rules on a dict of in-memory modules {relpath: source} — the
    multi-file test harness for cross-module rules. All modules join one
    ProjectInfo (symbol table / call graph / actor index span the dict);
    findings from every module come back in one sorted list."""
    from ray_tpu.tools.lint.project import ProjectInfo  # noqa: PLC0415

    modules = [
        ModuleInfo(Path(relpath), relpath, source)
        for relpath, source in sources.items()
    ]
    ProjectInfo(modules)
    full_run = rules is None
    rules = list(rules) if rules is not None else all_rules()
    raw: List[Finding] = []
    for module in modules:
        raw.extend(module.suppression_findings())
        for rule in rules:
            raw.extend(rule.check(module))
    raw.sort(key=Finding.key)
    # A cross-module rule can attribute a finding to the DEFINING module
    # while checking the importing one — classify suppressions by the
    # finding's own path, exactly as lint_paths does.
    sups_by_path = {m.relpath: m.suppressions for m in modules}
    out: List[Finding] = []
    for f in raw:
        sup = _matching_suppression(
            sups_by_path.get(f.path, {}).get(f.line), f
        )
        if sup is not None:
            sup.used = True
            continue
        out.append(f)
    if full_run:
        for module in modules:
            out.extend(
                _unused_suppression_findings(
                    module.suppressions, module.relpath
                )
            )
        out.sort(key=Finding.key)
    return out
