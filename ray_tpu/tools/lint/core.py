"""Core of `ray-tpu lint`: findings, module model, rule registry, runner.

A rule is a class with an `id` (stable, e.g. "RTL201"), a short `name`,
a `family` (async / locks / trace / resources) and a `check(module)`
returning findings. Rules work on a `ModuleInfo` — one parsed file plus
the derived maps every rule needs (import aliases, AST parent links,
inline suppressions) so each rule stays a focused AST pass.

Suppression idiom (reason is REQUIRED — an unexplained ignore is itself
reported as RTL002):

    do_risky_thing()  # ray-tpu: lint-ignore[RTL201] probe reads a stale
                      # bool at worst; the lock would serialize the loop

A standalone suppression comment applies to the next code line. Findings
neither fixed nor suppressible inline live in the checked-in baseline
(see baseline.py) with a written reason per entry.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

FAMILIES = ("meta", "async", "locks", "trace", "resources")

SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist", "node_modules"}
SKIP_FILE_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")

_SUPPRESS_RE = re.compile(
    r"#\s*ray-tpu:\s*lint-ignore\[([^\]]*)\]\s*(.*)$"
)


@dataclasses.dataclass
class Finding:
    rule: str
    name: str
    family: str
    path: str  # repo-relative posix path
    line: int
    col: int
    context: str  # dotted qualname of the enclosing scope
    message: str
    fingerprint: str = ""

    def key(self) -> tuple:
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _fingerprint(rule: str, path: str, context: str, line_text: str,
                 occurrence: int) -> str:
    # Line NUMBERS drift with every edit; the fingerprint hashes the rule,
    # file, enclosing scope and the normalized source text instead, so a
    # baseline survives unrelated churn above the finding.
    normalized = "".join(line_text.split())
    payload = f"{rule}|{path}|{context}|{normalized}|{occurrence}"
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


class Suppression:
    __slots__ = ("line", "ids", "reason", "used")

    def __init__(self, line: int, ids: set, reason: str):
        self.line = line
        self.ids = ids
        self.reason = reason
        self.used = False

    def matches(self, finding: Finding) -> bool:
        return "*" in self.ids or finding.rule in self.ids or (
            finding.name in self.ids
        )


def _matching_suppression(
    sups: Optional[List[Suppression]], finding: Finding
) -> Optional[Suppression]:
    """First suppression on the finding's line that names its rule AND
    carries a reason. RTL002 (reasonless ignore) is never suppressible."""
    if not sups or finding.rule == "RTL002":
        return None
    for sup in sups:
        if sup.reason and sup.matches(finding):
            return sup
    return None


_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class ModuleInfo:
    """One parsed source file plus the shared derived structure.

    Everything rules repeatedly need is computed in ONE traversal:
    parent links, a by-type node index, and scope ownership (each node
    mapped to its nearest enclosing function/lambda/module), so rules
    never re-walk the whole tree. A per-module memo dict lets rules
    share expensive derived maps (lock attrs, jitted functions)."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.parents: Dict[int, ast.AST] = {}
        self.by_type: Dict[type, List[ast.AST]] = {}
        # scope node (Module/FunctionDef/AsyncFunctionDef/Lambda) id ->
        # nodes owned directly by that scope (not by a nested scope).
        self.scope_nodes: Dict[int, List[ast.AST]] = {id(self.tree): []}
        self.scopes: List[ast.AST] = [self.tree]
        self.memo: Dict[str, object] = {}
        stack = [(self.tree, self.tree)]
        while stack:
            node, scope = stack.pop()
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
                self.by_type.setdefault(type(child), []).append(child)
                child_scope = scope
                if isinstance(child, _SCOPE_TYPES):
                    self.scopes.append(child)
                    self.scope_nodes[id(child)] = []
                    child_scope = child
                else:
                    self.scope_nodes[id(scope)].append(child)
                stack.append((child, child_scope))
        # name -> dotted module ("np" -> "numpy"); from-imports map the
        # bound name to "module.attr" ("jit" -> "jax.jit").
        self.aliases: Dict[str, str] = {}
        for node in self.nodes(ast.Import):
            for a in node.names:
                self.aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        for node in self.nodes(ast.ImportFrom):
            if not node.module:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        self.suppressions = self._parse_suppressions()
        self._expand_suppressions()

    def nodes(self, *types: type) -> List[ast.AST]:
        if len(types) == 1:
            return self.by_type.get(types[0], [])
        out: List[ast.AST] = []
        for t in types:
            out.extend(self.by_type.get(t, []))
        return out

    def own_nodes(self, scope: ast.AST) -> List[ast.AST]:
        """Nodes owned directly by `scope`, excluding nested functions."""
        return self.scope_nodes.get(id(scope), [])

    # -- suppressions -------------------------------------------------------

    def _parse_suppressions(self) -> Dict[int, List[Suppression]]:
        # A list per line: several standalone lint-ignore comments stacked
        # above one statement all resolve to that statement's line, and
        # each must keep its own ids + reason.
        # Lines inside multi-line string literals are string CONTENT, not
        # comments — a docstring showing the idiom must not register.
        in_string: set = set()
        for node in self.nodes(ast.Constant):
            if (
                isinstance(node.value, str)
                and getattr(node, "end_lineno", node.lineno) > node.lineno
            ):
                in_string.update(range(node.lineno, node.end_lineno + 1))
        out: Dict[int, List[Suppression]] = {}
        for i, text in enumerate(self.lines, start=1):
            if i in in_string:
                continue
            m = _SUPPRESS_RE.search(text)
            if m is None:
                continue
            ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
            reason = m.group(2).strip()
            line = i
            if text.lstrip().startswith("#"):
                # Standalone comment: applies to the next code line.
                j = i + 1
                while j <= len(self.lines) and (
                    not self.lines[j - 1].strip()
                    or self.lines[j - 1].lstrip().startswith("#")
                ):
                    j += 1
                line = j
            out.setdefault(line, []).append(Suppression(line, ids, reason))
        return out

    def _expand_suppressions(self) -> None:
        """Extend each suppression across the statement it anchors to, so
        an ignore above a black-wrapped expression reaches findings whose
        AST node sits on a continuation line. Compound statements extend
        over their HEADER only (`with`/`if`/`def` lines up to the first
        body statement) — an ignore must never blanket a whole block."""
        if not self.suppressions:
            return
        spans: Dict[int, int] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt):
                continue
            body = getattr(node, "body", None)
            if isinstance(body, list) and body and hasattr(body[0], "lineno"):
                end = max(node.lineno, body[0].lineno - 1)
            else:
                end = getattr(node, "end_lineno", None) or node.lineno
            prev = spans.get(node.lineno)
            spans[node.lineno] = end if prev is None else max(prev, end)
        for line, sups in list(self.suppressions.items()):
            for extra in range(line + 1, spans.get(line, line) + 1):
                self.suppressions.setdefault(extra, []).extend(sups)

    def suppression_findings(self) -> List[Finding]:
        """RTL002: a lint-ignore with no written reason is not a valid
        suppression (and does not suppress anything)."""
        out = []
        # Expansion aliases one Suppression onto several lines — report
        # each object once, at its anchor.
        unique = {
            id(s): s for sups in self.suppressions.values() for s in sups
        }
        for sup in unique.values():
            if not sup.reason:
                out.append(
                    Finding(
                        rule="RTL002",
                        name="suppression-missing-reason",
                        family="meta",
                        path=self.relpath,
                        line=sup.line,
                        col=0,
                        context="<module>",
                        message=(
                            "lint-ignore without a reason; write why the "
                            "finding is a false positive after the bracket"
                        ),
                    )
                )
        return out

    # -- resolution helpers -------------------------------------------------

    def dotted_name(self, node: ast.AST) -> Optional[str]:
        """`a.b.c` for an Attribute/Name chain, with the root mapped
        through the module's import aliases. None for dynamic receivers."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def call_target(self, call: ast.Call) -> Optional[str]:
        return self.dotted_name(call.func)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


def qualname_of(module: ModuleInfo, node: ast.AST) -> str:
    """Dotted path of the scopes enclosing `node` (classes + functions)."""
    parts: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            parts.append(cur.name)
        cur = module.parent(cur)
    return ".".join(reversed(parts)) or "<module>"


class Rule:
    id = "RTL000"
    name = "abstract"
    family = "meta"
    description = ""

    def check(self, module: ModuleInfo) -> List[Finding]:
        raise NotImplementedError

    def finding(
        self, module: ModuleInfo, node: ast.AST, message: str
    ) -> Finding:
        return Finding(
            rule=self.id,
            name=self.name,
            family=self.family,
            path=module.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            context=qualname_of(module, node),
            message=message,
        )


def all_rules() -> List[Rule]:
    from ray_tpu.tools.lint import (  # noqa: PLC0415 — avoid import cycle
        rules_async,
        rules_locks,
        rules_resources,
        rules_trace,
    )

    rules: List[Rule] = []
    for mod in (rules_async, rules_locks, rules_trace, rules_resources):
        rules.extend(r() for r in mod.RULES)
    return rules


def iter_python_files(paths: Sequence[Path]) -> Iterable[Path]:
    # Overlapping scan paths (`lint ray_tpu ray_tpu/_private`) must not
    # yield a file twice: duplicate findings get occurrence-shifted
    # fingerprints that no longer match the baseline.
    seen = set()
    for path in paths:
        if path.is_file():
            if path.suffix == ".py" and path.resolve() not in seen:
                seen.add(path.resolve())
                yield path
            continue
        for sub in sorted(path.rglob("*.py")):
            # Only components BELOW the scan root count: a checkout that
            # happens to live under ~/.cache or a dir named `build` must
            # not make the whole scan vacuously clean.
            if any(part in SKIP_DIRS or part.startswith(".")
                   for part in sub.relative_to(path).parts):
                continue
            if sub.name.endswith(SKIP_FILE_SUFFIXES):
                continue
            resolved = sub.resolve()
            if resolved in seen:
                continue
            seen.add(resolved)
            yield sub


def find_repo_root(start: Path) -> Path:
    """Directory the baseline lives in: nearest ancestor (of the first
    scanned path) holding a pyproject.toml, else the CWD."""
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    for candidate in (cur, *cur.parents):
        if (candidate / "pyproject.toml").exists():
            return candidate
    return Path.cwd()


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # active (not suppressed, not baselined)
    suppressed: List[Tuple[Finding, str]]  # (finding, reason)
    baselined: List[Tuple[Finding, str]]
    parse_errors: List[Finding]
    files_scanned: int
    duration_s: float
    stale_baseline: List[str] = dataclasses.field(default_factory=list)


def _unused_suppression_findings(
    suppressions: Dict[int, List[Suppression]], relpath: str
) -> List[Finding]:
    """RTL003: a reasoned lint-ignore whose finding no longer fires is
    rot — the hazard was fixed (delete the comment) or the comment
    drifted off the flagged statement (it no longer protects anything).
    Only meaningful when the FULL rule registry ran: under --rule the
    other rules' suppressions legitimately match nothing."""
    out = []
    unique = {id(s): s for sups in suppressions.values() for s in sups}
    for sup in unique.values():
        if sup.reason and not sup.used:
            out.append(
                Finding(
                    rule="RTL003",
                    name="unused-suppression",
                    family="meta",
                    path=relpath,
                    line=sup.line,
                    col=0,
                    context="<module>",
                    message=(
                        "lint-ignore["
                        + ",".join(sorted(sup.ids))
                        + "] suppresses nothing; delete it or re-anchor "
                        "it to the flagged statement"
                    ),
                )
            )
    return out


def lint_paths(
    paths: Sequence[Path],
    rules: Optional[Sequence[Rule]] = None,
    rule_ids: Optional[Sequence[str]] = None,
    baseline: Optional[dict] = None,
    root: Optional[Path] = None,
) -> LintResult:
    t0 = time.perf_counter()
    full_run = rules is None and not rule_ids
    rules = list(rules) if rules is not None else all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        rules = [r for r in rules if r.id in wanted or r.name in wanted]
    root = root or find_repo_root(Path(paths[0]))
    baseline = baseline or {}

    raw: List[Finding] = []
    parse_errors: List[Finding] = []
    suppressions_by_file: Dict[str, Dict[int, List[Suppression]]] = {}
    lines_by_file: Dict[str, List[str]] = {}
    n_files = 0
    for file in iter_python_files([Path(p) for p in paths]):
        n_files += 1
        try:
            relpath = file.resolve().relative_to(root).as_posix()
        except ValueError:
            relpath = file.as_posix()
        try:
            module = ModuleInfo(file, relpath, file.read_text())
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            parse_errors.append(
                Finding(
                    rule="RTL001",
                    name="parse-error",
                    family="meta",
                    path=relpath,
                    line=getattr(exc, "lineno", 0) or 0,
                    col=0,
                    context="<module>",
                    message=f"could not parse: {exc}",
                )
            )
            continue
        suppressions_by_file[relpath] = module.suppressions
        lines_by_file[relpath] = module.lines
        raw.extend(module.suppression_findings())
        for rule in rules:
            raw.extend(rule.check(module))

    raw.sort(key=Finding.key)
    # Occurrence-stable fingerprints for findings that normalize to the
    # same source text within one scope.
    seen: Dict[tuple, int] = {}
    for f in raw:
        lines = lines_by_file.get(f.path, [])
        line_text = lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        base = (f.rule, f.path, f.context, "".join(line_text.split()))
        occ = seen.get(base, 0)
        seen[base] = occ + 1
        f.fingerprint = _fingerprint(f.rule, f.path, f.context, line_text, occ)

    active: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    baselined: List[Tuple[Finding, str]] = []
    produced = set()
    for f in raw:
        produced.add(f.fingerprint)
        sup = _matching_suppression(
            suppressions_by_file.get(f.path, {}).get(f.line), f
        )
        if sup is not None:
            sup.used = True
            suppressed.append((f, sup.reason))
            continue
        if f.fingerprint in baseline:
            baselined.append((f, baseline[f.fingerprint].get("reason", "")))
            continue
        active.append(f)

    if full_run:
        # Orphaned suppressions are only knowable after every rule had
        # its chance to match them, so they classify here (baseline
        # honored; inline self-suppression would be circular, skipped).
        orphans: List[Finding] = []
        for relpath, sups in suppressions_by_file.items():
            orphans.extend(_unused_suppression_findings(sups, relpath))
        orphans.sort(key=Finding.key)
        for f in orphans:
            lines = lines_by_file.get(f.path, [])
            line_text = (
                lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
            )
            base = (f.rule, f.path, f.context, "".join(line_text.split()))
            occ = seen.get(base, 0)
            seen[base] = occ + 1
            f.fingerprint = _fingerprint(
                f.rule, f.path, f.context, line_text, occ
            )
            produced.add(f.fingerprint)
            if f.fingerprint in baseline:
                baselined.append(
                    (f, baseline[f.fingerprint].get("reason", ""))
                )
            else:
                active.append(f)
        active.sort(key=Finding.key)

    # Stale = the scan COULD have re-produced the entry (its file was
    # scanned with its rule active) and did not. A path- or rule-scoped
    # run must not report the rest of the baseline as stale.
    scanned_rule_ids = {r.id for r in rules}
    scanned_relpaths = set(lines_by_file)
    stale = [
        fp for fp, entry in baseline.items()
        if fp not in produced
        and entry.get("rule") in scanned_rule_ids
        and entry.get("path") in scanned_relpaths
    ]
    return LintResult(
        findings=active,
        suppressed=suppressed,
        baselined=baselined,
        parse_errors=parse_errors,
        files_scanned=n_files,
        duration_s=time.perf_counter() - t0,
        stale_baseline=stale,
    )


def lint_source(
    source: str,
    rules: Optional[Sequence[Rule]] = None,
    relpath: str = "<snippet>.py",
) -> List[Finding]:
    """Run rules on an in-memory snippet (test harness entry point);
    returns ALL findings, honoring inline suppressions but no baseline."""
    module = ModuleInfo(Path(relpath), relpath, source)
    full_run = rules is None
    rules = list(rules) if rules is not None else all_rules()
    raw = list(module.suppression_findings())
    for rule in rules:
        raw.extend(rule.check(module))
    raw.sort(key=Finding.key)
    out = []
    for f in raw:
        sup = _matching_suppression(module.suppressions.get(f.line), f)
        if sup is not None:
            sup.used = True
            continue
        out.append(f)
    if full_run:
        out.extend(
            _unused_suppression_findings(module.suppressions, relpath)
        )
        out.sort(key=Finding.key)
    return out
