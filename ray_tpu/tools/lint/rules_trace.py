"""Family 3 — JIT trace-safety + clock-discipline rules.

RTL301: host side effects inside a function handed to `jax.jit` / `pjit`
/ `shard_map` (including `@jax.jit`, `@partial(jax.jit, ...)` and
`jax_compat` wrapper forms). Side effects run ONCE at trace time and
never again — `time.time()`, host `random`, metric writes and `print`
inside a jitted function silently produce wrong-but-fast programs
(the constant from trace time is baked into the compiled executable).

RTL303: mutation of closed-over / self state inside a jitted function —
same trace-once hazard for state instead of values.

Both rules also cover Pallas kernel bodies (`pl.pallas_call(kernel, ...)`,
including `functools.partial(kernel, ...)` forms): a kernel is traced
exactly like a jitted function, so host side effects and closure mutation
inside it are the same silent trace-time-only bugs. Ref/scratch writes
(`o_ref[...] = x`) are writes to kernel *arguments* and are never flagged.

RTL302: durations or deadlines computed from `time.time()`. Wall clock
steps under NTP/suspend, so `deadline = time.time() + t` can hang or
fire early; `time.time() - t0` durations jitter. Use
`time.monotonic()`/`perf_counter()` unless wall-clock *identity* is
required (timestamps that are compared across processes, e.g. trace
spans).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ray_tpu.tools.lint.core import (
    Finding,
    ModuleInfo,
    Rule,
    _param_names,
    _resolve_function,
    _scope_level_nodes,
    _target_binds,
    resolve_function_ex,
)

JIT_WRAPPER_SUFFIXES = ("jit", "pjit", "pmap", "shard_map", "pallas_call")

IMPURE_CALL_PREFIXES = (
    "time.",
    "random.",
    "numpy.random.",
    "uuid.",
    "logging.",
)
PURE_TIME_EXCEPTIONS: Set[str] = set()  # all of time.* is host-side
IMPURE_BARE_CALLS = {"print", "open", "input"}
IMPURE_METHOD_CALLS = {"inc", "observe"}  # util.metrics write API
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "add", "remove",
    "discard", "clear", "pop", "popleft", "popitem", "put",
}


def _is_jit_wrapper(module: ModuleInfo, func: ast.AST) -> bool:
    dotted = module.dotted_name(func)
    if dotted is None:
        return False
    last = dotted.rsplit(".", 1)[-1]
    if last not in JIT_WRAPPER_SUFFIXES:
        return False
    if last in ("pjit", "shard_map", "pmap", "pallas_call"):
        return True
    # Bare `jit`: require a jax-ish origin so `obj.jit` elsewhere (or a
    # local helper named jit) doesn't fire.
    return dotted.startswith("jax.") or dotted.endswith(".jit") and (
        "jax" in dotted
    )


def _jitted_function_args(module: ModuleInfo, call: ast.Call):
    """The function-expression argument(s) of a jit-wrapper call."""
    out = []
    if call.args:
        out.append(call.args[0])
    for kw in call.keywords:
        if kw.arg in ("fun", "f", "func"):
            out.append(kw.value)
    return out


def find_jitted_functions(module: ModuleInfo):
    """(fn_node, wrapper_desc, defining_module) for every function this
    module hands to a jit-style wrapper, via call, decorator, or
    partial-decorator. Resolution crosses module boundaries (an imported
    step function handed to `jax.jit` is analyzed in ITS file, findings
    attributed there); a project-level seen-set keeps a function jitted
    from several modules from being flagged once per importer. Memoized
    per module (several rules consume it)."""
    cached = module.memo.get("jitted_functions")
    if cached is not None:
        return cached
    # Project-wide dedup: the defining module may jit the fn itself AND
    # be referenced by importers — whichever module is checked first owns
    # the (single) analysis of that function.
    seen = (
        module.project.memo.setdefault("jitted_seen_xmodule", set())
        if module.project is not None
        else set()
    )
    out = []
    for node in module.nodes(ast.Call):
        if _is_jit_wrapper(module, node.func):
            for arg in _jitted_function_args(module, node):
                resolved = resolve_function_ex(module, arg, node)
                if resolved is None:
                    continue
                def_module, fn = resolved
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                out.append(
                    (fn, module.dotted_name(node.func) or "jit", def_module)
                )
    for node in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        for dec in node.decorator_list:
            desc = _decorator_jit_desc(module, dec)
            if desc and id(node) not in seen:
                seen.add(id(node))
                out.append((node, desc, module))
    module.memo["jitted_functions"] = out
    return out


def _decorator_jit_desc(module: ModuleInfo, dec: ast.AST) -> Optional[str]:
    if _is_jit_wrapper(module, dec):
        return module.dotted_name(dec)
    if isinstance(dec, ast.Call):
        # @jax.jit(...) / @partial(jax.jit, ...) / @shard_map(...)
        if _is_jit_wrapper(module, dec.func):
            return module.dotted_name(dec.func)
        dotted = module.dotted_name(dec.func)
        if dotted and dotted.rsplit(".", 1)[-1] == "partial" and dec.args:
            if _is_jit_wrapper(module, dec.args[0]):
                return f"partial({module.dotted_name(dec.args[0])}, ...)"
    return None


class JitImpureCallRule(Rule):
    id = "RTL301"
    name = "jit-impure-call"
    family = "trace"
    description = (
        "host side effect inside a jitted function runs once at trace "
        "time and never again"
    )
    rationale = (
        "jit traces the Python function ONCE and replays the compiled "
        "program forever after: time.time(), host random, metric writes "
        "and print inside it run only at trace time — the value from "
        "that single run is baked into the executable as a constant, "
        "silently producing wrong-but-fast programs."
    )
    bad_example = """
        import time
        import jax

        @jax.jit
        def step(x):
            t = time.time()
            return x + t
    """
    good_example = """
        import time
        import jax

        @jax.jit
        def step(x, t):
            return x + t

        def run(x):
            return step(x, time.time())
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for fn, wrapper, def_module in find_jitted_functions(module):
            body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    label = self._impure_label(def_module, node)
                    if label is None:
                        continue
                    out.append(
                        self.finding(
                            def_module,
                            node,
                            f"{label} inside a function traced by "
                            f"{wrapper}: it runs once at trace time and "
                            "is baked into the compiled program",
                        )
                    )
        return out

    def _impure_label(self, module, call: ast.Call) -> Optional[str]:
        dotted = module.call_target(call)
        if dotted is not None:
            if dotted in IMPURE_BARE_CALLS:
                return f"{dotted}()"
            for prefix in IMPURE_CALL_PREFIXES:
                if dotted.startswith(prefix) or dotted == prefix[:-1]:
                    # jax.random is fine; host random/numpy.random is not.
                    if dotted.startswith("jax."):
                        return None
                    return f"{dotted}()"
            if dotted.endswith(".maybe_fail") or dotted == "maybe_fail":
                return "fault-injection hook maybe_fail()"
        func = call.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in IMPURE_METHOD_CALLS
        ):
            return f"metric write .{func.attr}()"
        return None


class JitClosureMutationRule(Rule):
    id = "RTL303"
    name = "jit-closure-mutation"
    family = "trace"
    description = (
        "mutating self/global/closed-over state inside a jitted function "
        "happens at trace time only"
    )
    rationale = (
        "the same trace-once hazard as RTL301, for state instead of "
        "values: a self/global/closure write inside a jitted function "
        "executes during tracing and never again — the counter stays at "
        "1, the cache holds a tracer. Return the value instead."
    )
    bad_example = """
        import jax

        log = []

        @jax.jit
        def bad(x):
            log.append(x)
            return x
    """
    good_example = """
        import jax

        @jax.jit
        def good(x):
            return x, x * 2  # return what the caller should record
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for fn, wrapper, def_module in find_jitted_functions(module):
            if isinstance(fn, ast.Lambda):
                continue  # lambdas cannot contain statements
            local_names = self._local_bindings(fn)
            for stmt in ast.walk(fn):
                if isinstance(stmt, (ast.Global, ast.Nonlocal)):
                    out.append(
                        self.finding(
                            def_module, stmt,
                            f"global/nonlocal write inside a function "
                            f"traced by {wrapper} mutates host state at "
                            "trace time only",
                        )
                    )
                elif isinstance(stmt, (ast.Assign, ast.AugAssign)):
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        desc = self._store_target_desc(t, local_names)
                        if desc is not None:
                            out.append(
                                self.finding(
                                    def_module, t,
                                    f"{desc} inside a function traced by "
                                    f"{wrapper} runs at trace time only; "
                                    "return the value instead",
                                )
                            )
                elif isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call
                ):
                    call = stmt.value
                    func = call.func
                    if (
                        isinstance(func, ast.Attribute)
                        and func.attr in MUTATOR_METHODS
                        and isinstance(func.value, ast.Name)
                        and func.value.id not in local_names
                    ):
                        out.append(
                            self.finding(
                                def_module, call,
                                f"{func.value.id}.{func.attr}(...) mutates "
                                f"closed-over state inside a function "
                                f"traced by {wrapper} (trace-time only)",
                            )
                        )
        return out

    @staticmethod
    def _store_target_desc(
        t: ast.AST, local_names: Set[str]
    ) -> Optional[str]:
        """Describe a store target that mutates self / closed-over state,
        or None when the target is purely local."""
        if (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
        ):
            return f"self.{t.attr} assignment"
        if isinstance(t, ast.Subscript):
            base = t.value
            if (
                isinstance(base, ast.Name)
                and base.id not in local_names
            ):
                return f"subscript write to closed-over {base.id}"
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                return f"subscript write to self.{base.attr}"
        return None

    @staticmethod
    def _local_bindings(fn) -> Set[str]:
        names = {a.arg for a in fn.args.args}
        names.update(a.arg for a in fn.args.posonlyargs)
        names.update(a.arg for a in fn.args.kwonlyargs)
        if fn.args.vararg:
            names.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            names.add(fn.args.kwarg.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                names.add(node.id)
        return names


class WallClockDurationRule(Rule):
    id = "RTL302"
    name = "wallclock-duration"
    family = "trace"
    description = (
        "duration/deadline arithmetic on time.time(); use "
        "time.monotonic()/perf_counter() unless wall-clock identity is "
        "required"
    )
    rationale = (
        "wall clock steps under NTP/suspend: `deadline = time.time() + "
        "t` can park a poller forever after a backward step, and "
        "`time.time() - t0` durations jitter. Monotonic clocks exist "
        "for exactly this; keep time.time() only where wall-clock "
        "IDENTITY matters (timestamps compared across processes)."
    )
    bad_example = """
        import time

        def wait_for(pred, timeout):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if pred():
                    return True
            return False
    """
    good_example = """
        import time

        def wait_for(pred, timeout):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return True
            return False
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        class_attrs = self._wallclock_self_attrs(module)
        for scope in module.scopes:
            if isinstance(scope, ast.Lambda):
                continue
            out.extend(self._check_scope(module, scope, class_attrs))
        return out

    def _wallclock_self_attrs(self, module) -> Set[str]:
        attrs = set()
        for node in module.nodes(ast.Assign):
            if self._is_time_call(module, node.value):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        attrs.add(t.attr)
        return attrs

    def _is_time_call(self, module, expr) -> bool:
        return (
            isinstance(expr, ast.Call)
            and module.call_target(expr) == "time.time"
        )

    def _check_scope(self, module, scope, class_attrs) -> List[Finding]:
        # Wall-clock-tainted names in this scope (transitive over simple
        # assignments), excluding nested function bodies.
        own_nodes = module.own_nodes(scope)
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in own_nodes:
                if not isinstance(node, ast.Assign):
                    continue
                if self._expr_tainted(module, node.value, tainted):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            changed = True
        findings = []
        for node in own_nodes:
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if self._side_tainted(module, node.left, tainted,
                                      class_attrs) and self._side_tainted(
                                          module, node.right, tainted,
                                          class_attrs):
                    findings.append(self._flag(module, node))
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(node.ops[0], (ast.Lt, ast.LtE, ast.Gt,
                                            ast.GtE)):
                    sides = [node.left, node.comparators[0]]
                    if any(self._is_time_call(module, s) for s in sides) and (
                        all(
                            self._side_tainted(module, s, tainted,
                                               class_attrs)
                            for s in sides
                        )
                    ):
                        findings.append(self._flag(module, node))
        return findings

    def _flag(self, module, node) -> Finding:
        return self.finding(
            module,
            node,
            "duration/deadline computed from time.time(); wall clock "
            "steps under NTP — use time.monotonic()/perf_counter() "
            "unless wall-clock identity is required",
        )

    def _side_tainted(self, module, expr, tainted, class_attrs) -> bool:
        if self._is_time_call(module, expr):
            return True
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return expr.attr in class_attrs
        return False

    def _expr_tainted(self, module, expr, tainted) -> bool:
        for node in ast.walk(expr):
            if self._is_time_call(module, node):
                return True
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ) and node.id in tainted:
                return True
        return False


RULES = [JitImpureCallRule, JitClosureMutationRule, WallClockDurationRule]
