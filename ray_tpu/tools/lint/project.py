"""Project-level model for `ray-tpu lint`: the cross-module layer.

PR 6's analyzer was deliberately intraprocedural — every rule saw one
`ModuleInfo` at a time. This module adds the structure the RTL5xx/6xx/7xx
families need, computed ONCE per scan and shared through `project.memo`:

  * a **symbol table** (`resolve`): dotted name -> defining module + AST
    node, following `import x as y` chains, `from x import y as z`, and
    re-exports through `__init__.py` (each hop resolves in the module
    that wrote the alias, so multi-file chains terminate correctly);
  * a **constant resolver** (`resolve_constant`): small literal values
    (strings, numbers, tuples of them) pulled through names and across
    modules — e.g. a mesh's axis-name tuple defined in
    `ray_tpu/parallel/mesh.py` and used at a `shard_map` call site two
    packages away;
  * a **call graph** (`call_graph`): function/method qualkey -> resolved
    callee qualkeys, built from the same `_resolve_function` binding
    semantics rules already use, now crossing files;
  * an **actor index** (`actor_index`): classes decorated
    `@ray_tpu.remote` or registered via `ray_tpu.remote(Cls)` (including
    `Handle = ray_tpu.remote(Cls)` aliases and classes imported under
    another name), plus which names each module knows them by — the
    reachability base for the RTL7xx deadlock rules.

Every resolver is conservative: unresolvable means "no answer", never a
guess — cross-module rules only fire on facts the table can prove.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.lint.core import (
    ModuleInfo,
    _resolve_function,
    _scope_level_nodes,
    module_name_for,
    qualname_of,
    resolve_name_binding,
)

# Dotted targets that register an actor class. `ray_tpu.api.remote` is the
# implementation home `ray_tpu.remote` re-exports.
REMOTE_TARGETS = ("ray_tpu.remote", "ray_tpu.api.remote")

_MAX_HOPS = 8  # alias/re-export chains (a cycle would otherwise loop)

# A local def shadowing one of these would be missed by the call graph —
# an acceptable (edge-dropping, never edge-inventing) trade for skipping
# the binding walk on the majority of all bare-name calls.
import builtins as _builtins

_BUILTIN_NAMES = frozenset(dir(_builtins))


@dataclasses.dataclass
class Symbol:
    """A project-resolved top-level (or class-level) definition."""

    module: ModuleInfo
    node: Optional[ast.AST]  # FunctionDef/ClassDef/Assign; None = module
    name: str
    qualname: str  # "ray_tpu.parallel.mesh.MeshSpec"


def qualkey(module: ModuleInfo, node: ast.AST) -> Tuple[str, str]:
    """Stable identity of a function/method across the project."""
    return (module.relpath, qualname_of(module, node) or getattr(
        node, "name", "<module>"
    ))


class ProjectInfo:
    """All scanned modules plus lazily-built cross-module structure."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = list(modules)
        self.by_relpath: Dict[str, ModuleInfo] = {
            m.relpath: m for m in modules
        }
        self.by_name: Dict[str, ModuleInfo] = {}
        for m in modules:
            self.by_name[module_name_for(m.relpath)] = m
        self.memo: Dict[str, object] = {}
        self._top_level: Dict[int, Dict[str, ast.AST]] = {}
        for m in modules:
            m.project = self

    # -- symbol table -------------------------------------------------------

    def top_level(self, module: ModuleInfo) -> Dict[str, ast.AST]:
        """name -> defining node at module scope (defs, classes, and the
        LAST module-level assignment of each name)."""
        cached = self._top_level.get(id(module))
        if cached is not None:
            return cached
        out: Dict[str, ast.AST] = {}
        for node in module.tree.body:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                out[node.name] = node
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ) and node.value is not None:
                out[node.target.id] = node
        self._top_level[id(module)] = out
        return out

    def resolve(self, dotted: str, _depth: int = 0) -> Optional[Symbol]:
        """Map an absolute dotted name (already passed through the using
        module's import aliases) to the defining module + node, following
        re-export chains. None for externals and dynamic values."""
        if not dotted or _depth > _MAX_HOPS:
            return None
        parts = dotted.split(".")
        # Longest module prefix wins: "ray_tpu.llm.engine.LLMServer"
        # resolves in ray_tpu/llm/engine.py, not as an attr chain on
        # ray_tpu/__init__.py.
        for cut in range(len(parts), 0, -1):
            mod = self.by_name.get(".".join(parts[:cut]))
            if mod is None:
                continue
            rest = parts[cut:]
            if not rest:
                return Symbol(mod, None, "", dotted)
            return self._resolve_in_module(mod, rest, dotted, _depth)
        return None

    def _resolve_in_module(
        self, mod: ModuleInfo, rest: List[str], dotted: str, _depth: int
    ) -> Optional[Symbol]:
        name = rest[0]
        defs = self.top_level(mod)
        node = defs.get(name)
        if node is not None:
            if len(rest) == 1:
                return Symbol(mod, node, name, dotted)
            if isinstance(node, ast.ClassDef):
                for member in node.body:
                    if isinstance(
                        member, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and member.name == rest[1] and len(rest) == 2:
                        return Symbol(mod, member, rest[1], dotted)
            return None
        alias = mod.aliases.get(name)
        if alias is not None:
            # Re-export: resolve the alias target in ITS module, keeping
            # any remaining attr path.
            return self.resolve(
                ".".join([alias, *rest[1:]]), _depth + 1
            )
        return None

    def resolve_expr(
        self, module: ModuleInfo, expr: ast.AST
    ) -> Optional[Symbol]:
        dotted = module.dotted_name(expr)
        if dotted is None:
            return None
        sym = self.resolve(dotted)
        if sym is not None:
            return sym
        # A name with no module prefix may simply be defined at the top
        # level of the USING module (aliases were already folded in by
        # dotted_name, so anything left is local or unresolvable).
        parts = dotted.split(".")
        if parts[0] in self.top_level(module):
            return self._resolve_in_module(module, parts, dotted, 0)
        return None

    # -- constants ----------------------------------------------------------

    def resolve_constant(
        self, module: ModuleInfo, expr: ast.AST,
        at: Optional[ast.AST] = None, _depth: int = 0
    ):
        """Evaluate small static values: literals, tuples/lists of them,
        and names bound to them (locally via the binding walk when `at`
        is given, else module-level — crossing modules through the symbol
        table). None when not statically known."""
        if expr is None or _depth > _MAX_HOPS:
            return None
        if isinstance(expr, ast.Constant):
            return expr.value
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = []
            for el in expr.elts:
                v = self.resolve_constant(module, el, at, _depth + 1)
                if v is None:
                    return None
                out.append(v)
            return tuple(out)
        if isinstance(expr, ast.Name) and at is not None:
            bind = resolve_name_binding(module, expr.id, at)
            if isinstance(bind, ast.Assign):
                return self.resolve_constant(
                    module, bind.value, bind, _depth + 1
                )
            if isinstance(bind, ast.AnnAssign) and bind.value is not None:
                return self.resolve_constant(
                    module, bind.value, bind, _depth + 1
                )
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            sym = self.resolve_expr(module, expr)
            if sym is None or sym.node is None:
                return None
            if isinstance(sym.node, ast.Assign):
                return self.resolve_constant(
                    sym.module, sym.node.value, sym.node, _depth + 1
                )
            if isinstance(
                sym.node, ast.AnnAssign
            ) and sym.node.value is not None:
                return self.resolve_constant(
                    sym.module, sym.node.value, sym.node, _depth + 1
                )
        return None

    # -- call graph ---------------------------------------------------------

    def call_graph(self) -> Dict[Tuple[str, str], Set[Tuple[str, str]]]:
        """caller qualkey -> set of resolved callee qualkeys. Callees
        resolve through local bindings (`_resolve_function` semantics),
        `self._method`, and the cross-module symbol table; dynamic
        receivers simply contribute no edge."""
        cached = self.memo.get("call_graph")
        if cached is not None:
            return cached
        graph: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for module in self.modules:
            for call in module.nodes(ast.Call):
                target = self._resolve_callee(module, call)
                if target is None:
                    continue
                scope = self._enclosing_function(module, call)
                caller = (
                    qualkey(module, scope)
                    if scope is not None
                    else (module.relpath, "<module>")
                )
                graph.setdefault(caller, set()).add(target)
        self.memo["call_graph"] = graph
        return graph

    def function_index(
        self,
    ) -> Dict[Tuple[str, str], Tuple[ModuleInfo, ast.AST]]:
        """qualkey -> (module, FunctionDef) for every function/method in
        the project — the lookup side of call_graph()."""
        cached = self.memo.get("function_index")
        if cached is not None:
            return cached
        out: Dict[Tuple[str, str], Tuple[ModuleInfo, ast.AST]] = {}
        for module in self.modules:
            for fn in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
                out[qualkey(module, fn)] = (module, fn)
        self.memo["function_index"] = out
        return out

    def _enclosing_function(self, module: ModuleInfo, node: ast.AST):
        cur = module.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = module.parent(cur)
        return None

    def _resolve_callee(
        self, module: ModuleInfo, call: ast.Call
    ) -> Optional[Tuple[str, str]]:
        func = call.func
        # Builtins can never be project edges; skipping them avoids the
        # binding walk on the bulk of all bare-name calls (the full-tree
        # scan budget lives or dies on this).
        if isinstance(func, ast.Name) and func.id in _BUILTIN_NAMES:
            return None
        # self.method() -> method of the enclosing class.
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            fn = _resolve_function(module, func, call)
            if fn is not None:
                return qualkey(module, fn)
            return None
        if isinstance(func, ast.Attribute):
            # Dotted receivers resolve through the symbol table only —
            # the binding walk can't see into attribute chains anyway.
            sym = self.resolve_expr(module, func)
            if sym is not None and isinstance(
                sym.node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                return qualkey(sym.module, sym.node)
            return None
        fn = _resolve_function(module, func, call)
        if fn is not None:
            return qualkey(module, fn)
        sym = self.resolve_expr(module, func)
        if sym is not None and isinstance(
            sym.node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
        ):
            return qualkey(sym.module, sym.node)
        return None

    # -- import graph (ray-tpu lint --changed) ------------------------------

    def _import_targets(self, module: ModuleInfo) -> Set[str]:
        """Every absolute dotted name `module` imports, raw: alias
        targets, bare `import pkg.mod` names (the alias map stores only
        "pkg" for those), and `from X import *` bases (which bind no
        alias at all)."""
        cached = module.memo.get("import_targets")
        if cached is not None:
            return cached
        targets: Set[str] = set(module.aliases.values())
        for node in module.nodes(ast.Import):
            for a in node.names:
                targets.add(a.name)
        for node in module.nodes(ast.ImportFrom):
            base = module._import_base(node)
            if base is not None:
                targets.add(base)
        module.memo["import_targets"] = targets
        return targets

    def import_deps(self) -> Dict[str, Set[str]]:
        """relpath -> relpaths of scanned modules it imports (through
        any alias: `import x`, `from x import y [as z]`, bare dotted
        imports, `import *`; re-export chains are NOT followed here — a
        changed re-exporting __init__ is itself an import of its
        sources, so the transitive closure covers them)."""
        cached = self.memo.get("import_deps")
        if cached is not None:
            return cached
        out: Dict[str, Set[str]] = {}
        for module in self.modules:
            deps: Set[str] = set()
            for alias in self._import_targets(module):
                parts = alias.split(".")
                # Longest module prefix wins, mirroring resolve():
                # "pkg.mod.Symbol" depends on pkg/mod.py, and plain
                # "pkg.mod" on the module itself (or its __init__).
                for cut in range(len(parts), 0, -1):
                    dep = self.by_name.get(".".join(parts[:cut]))
                    if dep is not None:
                        deps.add(dep.relpath)
                        break
            deps.discard(module.relpath)
            out[module.relpath] = deps
        self.memo["import_deps"] = out
        return out

    def reverse_import_closure(self, relpaths) -> Set[str]:
        """The given modules plus every scanned module that imports any
        of them, transitively — the set a diff-scoped lint run must
        re-check (cross-module rules can change their verdict in any
        importer of a changed file). A changed path with NO module in
        the scan (deleted or renamed) still seeds the closure with its
        former importers, matched by module name against each module's
        raw import targets — a pure deletion must re-check everything
        that resolved symbols through the deleted file."""
        deps = self.import_deps()
        importers: Dict[str, Set[str]] = {}
        for src, targets in deps.items():
            for t in targets:
                importers.setdefault(t, set()).add(src)
        stack = [p for p in relpaths if p in self.by_relpath]
        missing_names = [
            module_name_for(p)
            for p in relpaths
            if p not in self.by_relpath and p.endswith(".py")
        ]
        if missing_names:
            for module in self.modules:
                targets = self._import_targets(module)
                if any(
                    t == name or t.startswith(name + ".")
                    for name in missing_names
                    for t in targets
                ):
                    stack.append(module.relpath)
        out: Set[str] = set()
        while stack:
            p = stack.pop()
            if p in out:
                continue
            out.add(p)
            stack.extend(importers.get(p, ()))
        return out

    # -- actor index --------------------------------------------------------

    def actor_index(self) -> "ActorIndex":
        cached = self.memo.get("actor_index")
        if cached is not None:
            return cached
        index = ActorIndex(self)
        self.memo["actor_index"] = index
        return index


class ActorIndex:
    """Which classes run as actors, and the names each module knows their
    handles/classes by.

    classes:    class qualkey -> (module, ClassDef)
    registered: (module relpath, bound name) -> actor class qualkey, for
                `Handle = ray_tpu.remote(Cls)`-style registrations (the
                bound name constructs handles of Cls).
    """

    def __init__(self, project: ProjectInfo):
        self.project = project
        self.classes: Dict[Tuple[str, str], Tuple[ModuleInfo, ast.ClassDef]] = {}
        self.registered: Dict[Tuple[str, str], Tuple[str, str]] = {}
        for module in project.modules:
            self._scan(module)

    def _is_remote_target(self, module: ModuleInfo, expr: ast.AST) -> bool:
        dotted = module.dotted_name(expr)
        return dotted in REMOTE_TARGETS

    def _scan(self, module: ModuleInfo) -> None:
        for node in module.nodes(ast.ClassDef):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if self._is_remote_target(module, target):
                    self.classes[qualkey(module, node)] = (module, node)
                    break
        # MODULE-scope registrations only: a function-local
        # `h = ray_tpu.remote(Cls)` must not leak into a module-wide map
        # where an unrelated local `h` elsewhere would resolve to it.
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            key = self._registration_target(module, node.value)
            if key is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.registered[(module.relpath, t.id)] = key

    def _registration_target(
        self, module: ModuleInfo, expr: ast.AST
    ) -> Optional[Tuple[str, str]]:
        """`ray_tpu.remote(Cls)` (optionally `.options(...)`) -> Cls's
        qualkey; the class may live in another module or be imported
        under an alias."""
        # Unwrap .options(...) / other fluent chains.
        while isinstance(expr, ast.Call) and isinstance(
            expr.func, ast.Attribute
        ) and expr.func.attr == "options":
            expr = expr.func.value
        if not (
            isinstance(expr, ast.Call)
            and self._is_remote_target(module, expr.func)
            and expr.args
        ):
            return None
        cls = self.resolve_class(module, expr.args[0], expr)
        if cls is None:
            return None
        clsmod, clsnode = cls
        key = qualkey(clsmod, clsnode)
        self.classes.setdefault(key, (clsmod, clsnode))
        return key

    def resolve_class(
        self, module: ModuleInfo, expr: ast.AST, at: ast.AST
    ) -> Optional[Tuple[ModuleInfo, ast.ClassDef]]:
        """Resolve an expression naming a class — locally, through the
        symbol table, or through an import alias."""
        if isinstance(expr, ast.Name):
            bind = resolve_name_binding(module, expr.id, at)
            if isinstance(bind, ast.ClassDef):
                return (module, bind)
        sym = self.project.resolve_expr(module, expr)
        if sym is not None and isinstance(sym.node, ast.ClassDef):
            return (sym.module, sym.node)
        return None

    def handle_class(
        self, module: ModuleInfo, expr: ast.AST, at: ast.AST
    ) -> Optional[Tuple[str, str]]:
        """Actor class behind a handle-constructing expression:
        `ActorCls.remote(...)`, `ActorCls.options(...).remote(...)`,
        `ray_tpu.remote(Cls)[.options(...)].remote(...)`, or a registered
        handle name (`RemoteX = ray_tpu.remote(X)`) — local or imported."""
        if not (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "remote"
        ):
            return None
        base = expr.func.value  # the thing .remote() was called on
        while isinstance(base, ast.Call) and isinstance(
            base.func, ast.Attribute
        ) and base.func.attr == "options":
            base = base.func.value
        # ray_tpu.remote(Cls)....remote()
        if isinstance(base, ast.Call):
            return self._registration_target(module, base)
        # A decorated actor class used directly, or a registered name.
        cls = self.resolve_class(module, base, at)
        if cls is not None:
            key = qualkey(cls[0], cls[1])
            if key in self.classes:
                return key
            return None
        dotted = module.dotted_name(base)
        if dotted is None:
            return None
        # Registered handle name, local ("RemoteX") or imported
        # ("pkg.mod.RemoteX" via the alias map).
        if "." not in dotted:
            return self.registered.get((module.relpath, dotted))
        sym = self.project.resolve(dotted)
        if sym is not None and isinstance(sym.node, ast.Assign):
            return self._registration_target(sym.module, sym.node.value)
        return None

    def methods(self, key: Tuple[str, str]) -> Dict[str, ast.AST]:
        module, node = self.classes[key]
        return {
            m.name: m
            for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
