"""Family 2 — lock-coverage race detection.

RTL201 infers, per class, which `self.<attr>` state a lock protects: an
attribute MUTATED while holding `self._lock` (or an alias — a
`threading.Condition(self._lock)` acquires the same lock) is treated as
lock-guarded, and every access to it outside the lock, in any other
method, is a finding. Codebase-aware exemptions:

  * `__init__`/`__new__`/`__del__` run before/after concurrent access and
    are never flagged (and contribute no guard evidence).
  * Methods named `*_locked` or whose docstring says the caller must hold
    the lock (e.g. "Caller must hold self._lock.") are treated as holding
    every class lock — the repo's existing private-helper convention.

RTL202 flags bare `lock.acquire()` calls — a raise between acquire and
release leaks the lock; use `with`.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.lint.core import Finding, ModuleInfo, Rule

LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
}

MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "clear", "update", "pop", "popleft", "popitem",
    "setdefault", "put", "put_nowait", "move_to_end", "sort", "reverse",
}

_HOLDS_DOC_RE = re.compile(r"caller(s)?\s+(must\s+)?hold", re.IGNORECASE)

_SKIP_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}


def is_lock_ctor(module: ModuleInfo, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    target = module.call_target(node)
    return target in LOCK_CTORS


def class_lock_attrs(module: ModuleInfo, cls: ast.ClassDef) -> Dict[str, str]:
    """{attr -> canonical lock attr}: `self._work =
    threading.Condition(self._lock)` maps _work to _lock, so holding
    either counts as holding the one underlying lock. Memoized per class."""
    memo = module.memo.setdefault("class_lock_attrs", {})
    cached = memo.get(id(cls))
    if cached is not None:
        return cached
    locks: Dict[str, str] = {}
    pending_alias: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            continue
        if not is_lock_ctor(module, node.value):
            continue
        call = node.value
        alias_of: Optional[str] = None
        if (
            module.call_target(call) == "threading.Condition"
            and call.args
            and isinstance(call.args[0], ast.Attribute)
            and isinstance(call.args[0].value, ast.Name)
            and call.args[0].value.id == "self"
        ):
            alias_of = call.args[0].attr
        if alias_of is not None:
            pending_alias[target.attr] = alias_of
        else:
            locks[target.attr] = target.attr
    for attr, alias_of in pending_alias.items():
        locks[attr] = locks.get(alias_of, alias_of)
    memo[id(cls)] = locks
    return locks


def _method_assumes_held(fn: ast.AST) -> bool:
    if fn.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(fn) or ""
    return bool(_HOLDS_DOC_RE.search(doc))


class _Access:
    __slots__ = ("attr", "node", "held", "mutation", "method")

    def __init__(self, attr, node, held, mutation, method):
        self.attr = attr
        self.node = node
        self.held = held
        self.mutation = mutation
        self.method = method


class LockCoverageRule(Rule):
    id = "RTL201"
    name = "unlocked-attribute"
    family = "locks"
    description = (
        "attribute mutated under a lock in one method must not be "
        "read or written without it in another"
    )
    rationale = (
        "the class's own locking discipline defines which attributes are "
        "shared state: anything mutated under self._lock is contended, so "
        "a bare access elsewhere races the locked writers — torn reads, "
        "lost updates, check-then-act bugs. __init__, *_locked helpers "
        "and 'Caller must hold' docstrings are exempt."
    )
    bad_example = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def peek(self):
                return len(self._items)
    """
    good_example = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def peek(self):
                with self._lock:
                    return len(self._items)
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for node in module.nodes(ast.ClassDef):
            out.extend(self._check_class(module, node))
        return out

    def _check_class(
        self, module: ModuleInfo, cls: ast.ClassDef
    ) -> List[Finding]:
        locks = class_lock_attrs(module, cls)
        if not locks:
            return []
        all_locks = frozenset(locks.values())
        accesses: List[_Access] = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name in _SKIP_METHODS:
                continue
            if self._constructs_lock(module, stmt, locks):
                # A method that CREATES the class's locks (setup()-style
                # late init) is initialization: nothing can contend for a
                # lock that does not exist yet.
                continue
            base_held = all_locks if _method_assumes_held(stmt) else frozenset()
            self._collect(module, stmt, stmt.name, locks, base_held, accesses)

        # Guard evidence: locks held across at least one MUTATION of the
        # attribute (plain loads under a lock prove nothing — snapshot
        # reads of unguarded state are idiomatic).
        guarded: Dict[str, Set[str]] = {}
        witness: Dict[str, str] = {}
        for acc in accesses:
            if acc.mutation and acc.held:
                guarded.setdefault(acc.attr, set()).update(acc.held)
                witness.setdefault(acc.attr, acc.method)

        findings = []
        for acc in accesses:
            guards = guarded.get(acc.attr)
            if not guards:
                continue
            if acc.held & guards:
                continue
            lock_names = "/".join(sorted(f"self.{g}" for g in guards))
            findings.append(
                self.finding(
                    module,
                    acc.node,
                    f"self.{acc.attr} is mutated under {lock_names} "
                    f"(e.g. in {cls.name}.{witness[acc.attr]}) but "
                    f"accessed here without it",
                )
            )
        return findings

    @staticmethod
    def _constructs_lock(module, method, locks) -> bool:
        for node in ast.walk(method):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id == "self"
                and node.targets[0].attr in locks
                and is_lock_ctor(module, node.value)
            ):
                return True
        return False

    # -- per-method walk ----------------------------------------------------

    def _collect(
        self,
        module: ModuleInfo,
        method: ast.AST,
        method_name: str,
        locks: Dict[str, str],
        held: frozenset,
        accesses: List[_Access],
    ) -> None:
        self._visit_body(module, method.body, method_name, locks, held,
                         accesses)

    def _held_after_with(
        self, module: ModuleInfo, node: ast.With, locks: Dict[str, str],
        held: frozenset,
    ) -> frozenset:
        extra = set()
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in locks
            ):
                extra.add(locks[expr.attr])
        return held | extra if extra else held

    def _visit_body(self, module, body, method_name, locks, held, accesses):
        for stmt in body:
            self._visit_stmt(module, stmt, method_name, locks, held, accesses)

    def _visit_stmt(self, module, stmt, method_name, locks, held, accesses):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            # Nested defs (callbacks, worker closures) run on arbitrary
            # threads at arbitrary times — the lexical lock state is
            # meaningless there, so they neither prove guarding nor flag.
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = self._held_after_with(module, stmt, locks, held)
            for item in stmt.items:
                self._visit_expr(module, item.context_expr, method_name,
                                 locks, held, accesses)
            self._visit_body(module, stmt.body, method_name, locks, inner,
                             accesses)
            return
        if isinstance(stmt, ast.Assign):
            self._visit_expr(module, stmt.value, method_name, locks, held,
                             accesses)
            for target in stmt.targets:
                self._visit_target(module, target, method_name, locks, held,
                                   accesses)
            return
        if isinstance(stmt, ast.AugAssign):
            self._visit_expr(module, stmt.value, method_name, locks, held,
                             accesses)
            self._visit_target(module, stmt.target, method_name, locks, held,
                               accesses)
            return
        if isinstance(stmt, (ast.Delete,)):
            for target in stmt.targets:
                self._visit_target(module, target, method_name, locks, held,
                                   accesses)
            return
        # Generic statement: recurse into child statements with the same
        # held set, and scan its expressions.
        for field in ast.iter_child_nodes(stmt):
            if isinstance(field, ast.stmt):
                self._visit_stmt(module, field, method_name, locks, held,
                                 accesses)
            elif isinstance(field, ast.expr):
                self._visit_expr(module, field, method_name, locks, held,
                                 accesses)
            elif isinstance(field, (ast.excepthandler,)):
                self._visit_body(module, field.body, method_name, locks,
                                 held, accesses)

    def _visit_target(self, module, target, method_name, locks, held,
                      accesses):
        """Assignment target: `self.X = ...`, `self.X[k] = ...` and
        `self.X.y = ...` all mutate X."""
        attr = self._root_self_attr(target)
        if attr is not None and attr not in locks:
            accesses.append(
                _Access(attr, target, held, True, method_name)
            )
        # Subscript indices / nested tuples may contain loads.
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._visit_target(module, el, method_name, locks, held,
                                   accesses)
        elif isinstance(target, ast.Subscript):
            self._visit_expr(module, target.slice, method_name, locks, held,
                             accesses)

    def _visit_expr(self, module, expr, method_name, locks, held, accesses):
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # ast.walk descends into nested defs; skip their contents
                # by pruning here (walk is BFS — prune via containment
                # check below instead).
                continue
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ) and node.value.id == "self":
                if node.attr in locks:
                    continue
                if self._inside_nested_def(module, node, expr):
                    continue
                mutation = self._is_mutating_use(module, node)
                accesses.append(
                    _Access(node.attr, node, held, mutation, method_name)
                )

    @staticmethod
    def _root_self_attr(target: ast.AST) -> Optional[str]:
        node = target
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            parent = node.value
            if (
                isinstance(node, ast.Attribute)
                and isinstance(parent, ast.Name)
                and parent.id == "self"
            ):
                return node.attr
            node = parent
        return None

    def _inside_nested_def(self, module, node, stop) -> bool:
        if node is stop:
            # A bare `self.X` that IS the visited expression (e.g.
            # `return self.X`, an `if self.X:` test) — walking up from
            # its parent would run past `stop` to the enclosing method
            # and misclassify it as nested.
            return False
        cur = module.parent(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return True
            cur = module.parent(cur)
        return False

    def _is_mutating_use(self, module, node: ast.Attribute) -> bool:
        """`self.X.append(...)` / `self.X |= ...`-style mutations that
        appear as loads in the AST."""
        parent = module.parent(node)
        if (
            isinstance(parent, ast.Attribute)
            and parent.attr in MUTATOR_METHODS
        ):
            gp = module.parent(parent)
            if isinstance(gp, ast.Call) and gp.func is parent:
                return True
        return False


class ManualAcquireRule(Rule):
    id = "RTL202"
    name = "manual-lock-acquire"
    family = "locks"
    description = (
        "lock.acquire() outside a with-statement leaks the lock if "
        "anything between acquire and release raises"
    )
    rationale = (
        "an exception between acquire() and release() leaves the lock "
        "held forever — every later contender hangs. The with-statement "
        "releases on every exit path."
    )
    bad_example = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                self._lock.acquire()
                do_something()
                self._lock.release()
    """
    good_example = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def good(self):
                with self._lock:
                    do_something()
    """

    def check(self, module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        known_attrs = set()
        for cls in module.nodes(ast.ClassDef):
            known_attrs.update(class_lock_attrs(module, cls))
        for node in module.nodes(ast.Call):
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            recv = node.func.value
            is_lock = False
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and recv.attr in known_attrs
            ):
                is_lock = True
            elif isinstance(recv, ast.Name) and "lock" in recv.id.lower():
                is_lock = True
            if not is_lock:
                continue
            parent = module.parent(node)
            if isinstance(parent, ast.Await):
                continue  # asyncio primitive
            out.append(
                self.finding(
                    module,
                    node,
                    "bare lock.acquire(); use `with` so a raise between "
                    "acquire and release cannot leak the lock",
                )
            )
        return out


RULES = [LockCoverageRule, ManualAcquireRule]
