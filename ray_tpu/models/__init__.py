from ray_tpu.models.gpt import (
    GPT,
    GPTConfig,
    cross_entropy_loss,
    gpt2_125m,
    gpt2_350m,
    gpt2_760m,
)
from ray_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)

__all__ = [
    "GPT",
    "GPTConfig",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "cross_entropy_loss",
    "gpt2_125m",
    "gpt2_350m",
    "gpt2_760m",
]
