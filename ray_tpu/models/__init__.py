from ray_tpu.models.gpt import (
    GPT,
    GPTConfig,
    collect_kv_caches,
    collect_moe_losses,
    cross_entropy_loss,
    gpt2_125m,
    gpt2_350m,
    gpt2_760m,
)
from ray_tpu.models.moe import MoEConfig, MoEMlp
from ray_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)

__all__ = [
    "GPT",
    "GPTConfig",
    "MoEConfig",
    "MoEMlp",
    "collect_kv_caches",
    "collect_moe_losses",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "cross_entropy_loss",
    "gpt2_125m",
    "gpt2_350m",
    "gpt2_760m",
]
