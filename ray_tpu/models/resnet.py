"""ResNet family (flax), TPU-first.

The ResNet-50/ImageNet workload is the reference's headline Train benchmark
(release/air_tests/air_benchmarks/workloads/torch_benchmark.py; BASELINE.json
north-star "images/sec/chip"). Re-designed for TPU: NHWC layout (XLA's native
conv layout), bf16 activations with f32 params, and jit-pure normalization
(GroupNorm default — no cross-device batch-stat sync needed; BatchNorm
available via norm='batch' with mutable batch_stats).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """Bottleneck block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    norm: str = "group"  # "group" | "batch"
    small_inputs: bool = False  # CIFAR-style stem (3x3, no maxpool)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        if self.norm == "batch":
            norm = functools.partial(
                nn.BatchNorm, use_running_average=not train, momentum=0.9,
                epsilon=1e-5, dtype=self.dtype,
            )
        else:
            norm = functools.partial(nn.GroupNorm, num_groups=32, dtype=self.dtype)
        act = nn.relu

        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="norm_init")(x)
        x = act(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    act=act,
                    strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = functools.partial(
    ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock
)
ResNet152 = functools.partial(
    ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock
)
