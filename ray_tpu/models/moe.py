"""Mixture-of-Experts feed-forward with expert parallelism.

The reference has no MoE/expert-parallel support at all (SURVEY.md §2.4:
"Expert parallel (EP/MoE) — Absent"); this is designed fresh for TPU in the
GShard/Switch style: routing is expressed as dense one-hot dispatch/combine
einsums over an `expert` axis, so when the expert dim is sharded on the `ep`
mesh axis (EP_RULES) XLA lowers the dispatch to all-to-alls over ICI — no
hand-written token shuffling. Capacity-factor dropping keeps every shape
static (XLA requirement); dropped tokens pass through the residual.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    num_experts_per_tok: int = 2  # top-k routing
    capacity_factor: float = 1.25
    router_z_loss_coef: float = 1e-3
    load_balance_loss_coef: float = 1e-2


class MoEMlp(nn.Module):
    """Drop-in replacement for a dense transformer MLP block.

    Returns (output, aux_losses) where aux_losses carries the router z-loss
    and the Switch load-balancing loss — the caller folds them into the
    training objective.
    """

    embed_dim: int
    mlp_dim: int
    moe: MoEConfig = MoEConfig()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray):
        cfg = self.moe
        b, s, d = x.shape
        n_tokens = b * s
        E = cfg.num_experts
        k = min(cfg.num_experts_per_tok, E)
        # Static per-expert capacity (padded shapes → compilable).
        capacity = max(1, int(cfg.capacity_factor * n_tokens * k / E))

        tokens = x.reshape(n_tokens, d)

        # Router (always f32: small matmul, numerically sensitive).
        router_w = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "expert")
            ),
            (d, E),
            jnp.float32,
        )
        logits = tokens.astype(jnp.float32) @ router_w  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # Top-k expert choice per token.
        gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [T, k]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9
        )

        # Position of each (token, choice) within its expert's capacity
        # buffer. Positions are assigned choice-major (all 1st choices across
        # every token first, then 2nd choices, ...) so under tight capacity a
        # token's secondary choice never evicts another token's primary —
        # the GShard/Switch priority rule.
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # [T, k, E]
        choice_major = onehot.swapaxes(0, 1).reshape(k * n_tokens, E)
        position = (jnp.cumsum(choice_major, axis=0) - 1).reshape(
            k, n_tokens, E
        ).swapaxes(0, 1)  # [T, k, E]
        position = (position * onehot).sum(-1)  # [T, k]
        within_cap = position < capacity

        # dispatch [T, E, C]: 0/1 routing; combine carries the gate weights
        # for the return trip. Accumulated one choice at a time — the full
        # [T, k, E, C] tensor would be k× larger for no reason.
        dispatch = jnp.zeros((n_tokens, E, capacity), self.dtype)
        combine = jnp.zeros((n_tokens, E, capacity), self.dtype)
        for j in range(k):
            slot = (
                jax.nn.one_hot(expert_idx[:, j], E, dtype=self.dtype)[..., None]
                * jax.nn.one_hot(position[:, j], capacity, dtype=self.dtype)[:, None, :]
                * within_cap[:, j, None, None].astype(self.dtype)
            )
            dispatch = dispatch + slot
            combine = combine + slot * gate_vals[:, j, None, None].astype(self.dtype)

        # Expert buffers: [E, C, d] — the einsum XLA turns into an
        # all-to-all when `expert` is sharded on ep.
        expert_in = jnp.einsum("td,tec->ecd", tokens.astype(self.dtype), dispatch)

        w_in = self.param(
            "w_in",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("expert", "embed", "mlp")
            ),
            (E, d, self.mlp_dim),
            self.dtype,
        )
        w_out = self.param(
            "w_out",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("expert", "mlp", "embed")
            ),
            (E, self.mlp_dim, d),
            self.dtype,
        )
        h = jnp.einsum("ecd,edm->ecm", expert_in, w_in)
        h = nn.gelu(h)
        expert_out = jnp.einsum("ecm,emd->ecd", h, w_out)

        # Combine back to token order, weighted by gates.
        out = jnp.einsum("ecd,tec->td", expert_out, combine)
        out = out.reshape(b, s, d)

        # Aux losses (Switch Transformer): z-loss + load balancing.
        z_loss = jnp.mean(jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
        # fraction of tokens routed (top-1) per expert × mean router prob.
        top1 = jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32)
        load = top1.mean(axis=0)
        importance = probs.mean(axis=0)
        balance_loss = E * jnp.sum(load * importance)
        aux = {
            "router_z_loss": cfg.router_z_loss_coef * z_loss,
            "load_balance_loss": cfg.load_balance_loss_coef * balance_loss,
        }
        return out, aux
