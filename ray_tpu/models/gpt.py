"""GPT-2-style decoder-only transformer (flax), TPU-first.

The "GPT-2 125M language modeling" config from BASELINE.json. Every weight
carries *logical* axis names via nn.with_logical_partitioning, so one model
definition serves dp / fsdp / tp / sp by swapping the rules table
(ray_tpu.parallel.sharding) — the design that replaces the reference's
FSDP/DeepSpeed integration wrappers (train/huggingface/accelerate/).

Sequence parallelism: attention goes through ray_tpu.ops (flash kernel on TPU;
ring attention when the caller runs the model under shard_map with the seq dim
sharded on `sp`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops import attention as attention_op
from ray_tpu.ops.attention import head_sharded_attention
from ray_tpu.ops.flash_attention import flash_attention_packed
from ray_tpu.ops.paged_flash import paged_attention_impl
from ray_tpu.ops.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # 50257 padded to a multiple of 128 for the MXU
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_ratio: int = 4
    max_seq_len: int = 1024
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    # "flash" (pallas kernel), "reference", or "ring" (requires sp-sharded
    # inputs under shard_map with axis name `sp`).
    attention_impl: str = "flash"
    # MoE: num_experts=0 keeps dense MLPs; otherwise every `moe_every`-th
    # block swaps its MLP for a MoEMlp (experts shard on the ep mesh axis).
    num_experts: int = 0
    moe_every: int = 2
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 1.25

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def gpt2_125m(**overrides) -> "GPTConfig":
    return GPTConfig(**overrides)


def gpt2_350m(**overrides) -> "GPTConfig":
    return GPTConfig(num_layers=24, num_heads=16, embed_dim=1024, **overrides)


def gpt2_760m(**overrides) -> "GPTConfig":
    return GPTConfig(num_layers=24, num_heads=20, embed_dim=1280, **overrides)


def _dense(features, logical_axes, dtype, name=None, use_bias=True):
    return nn.Dense(
        features,
        dtype=dtype,
        use_bias=use_bias,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), logical_axes
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros, (logical_axes[-1],)
        ),
        name=name,
    )


class Block(nn.Module):
    config: GPTConfig
    use_moe: bool = False

    @nn.compact
    def __call__(
        self,
        x,
        deterministic: bool = True,
        *,
        return_kv: bool = False,
        paged_state: Optional[tuple] = None,
        paged_impl: str = "reference",
        paged_mesh: Optional[Any] = None,
    ):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_1")(x)
        b, s, _ = h.shape
        qkv = _dense(3 * cfg.embed_dim, ("embed", "heads"), cfg.dtype, name="attn_qkv")(h)
        if return_kv or paged_state is not None:
            # Generation paths (ray_tpu.llm). All need this layer's K/V
            # exposed: prefill sows the prompt's K/V for the engine to
            # scatter into the paged cache; decode (s == 1) and prefix-aware
            # partial prefill (s > 1, uncached suffix only) attend over the
            # cache through the block table — paged over the cached prefix,
            # causal among the fed tokens — and sow the new K/V.
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
            k = k.reshape(b, s, cfg.num_heads, cfg.head_dim)
            v = v.reshape(b, s, cfg.num_heads, cfg.head_dim)
            if paged_state is not None:
                (k_cache_l, v_cache_l, block_tables, context_lens,
                 k_scale_l, v_scale_l) = paged_state
                # "pallas" runs the fused kernel (walks the block table
                # inside the pipeline, never materializing the gathered
                # pages or the logits — ops/paged_flash.py); "reference"
                # the XLA gather+softmax op. The engine resolves "auto"
                # before tracing, so the choice is compile-time static.
                attn = paged_attention_impl(
                    q, k_cache_l, v_cache_l, block_tables, context_lens,
                    new_k=k, new_v=v,
                    k_scale=k_scale_l, v_scale=v_scale_l,
                    impl=paged_impl,
                    mesh=paged_mesh,
                )
            else:
                impl = (
                    "reference"
                    if cfg.attention_impl == "ring"
                    else cfg.attention_impl
                )
                if (
                    paged_mesh is not None
                    and paged_mesh.shape.get("tp", 1) > 1
                ):
                    # Full prefill under tensor parallelism: heads are
                    # independent in attention, so the dense causal pass
                    # runs head-sliced over the same tp axis as the paged
                    # programs (the flash kernel can't be auto-partitioned
                    # by GSPMD — each shard runs it over its local heads).
                    attn = head_sharded_attention(
                        paged_mesh, q, k, v, impl=impl
                    )
                else:
                    attn = attention_op(q, k, v, causal=True, impl=impl)
            self.sow("intermediates", "kv_cache", (k, v))
            attn = attn.reshape(b, s, cfg.embed_dim)
        elif cfg.attention_impl == "flash" and s <= 2048:
            # Packed kernel consumes the projection output directly: no
            # split / head reshape / fold transposes in the graph, dqkv
            # comes back packed for the projection's grad matmul.
            attn = flash_attention_packed(qkv, cfg.num_heads, causal=True)
        else:
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
            k = k.reshape(b, s, cfg.num_heads, cfg.head_dim)
            v = v.reshape(b, s, cfg.num_heads, cfg.head_dim)
            if cfg.attention_impl == "ring":
                attn = ring_attention(q, k, v, axis_name="sp", causal=True)
            else:
                attn = attention_op(q, k, v, causal=True, impl=cfg.attention_impl)
            attn = attn.reshape(b, s, cfg.embed_dim)
        attn = _dense(cfg.embed_dim, ("heads", "embed"), cfg.dtype, name="attn_proj")(attn)
        x = x + attn
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_2")(x)
        if self.use_moe:
            from ray_tpu.models.moe import MoEConfig, MoEMlp

            h, aux = MoEMlp(
                embed_dim=cfg.embed_dim,
                mlp_dim=cfg.mlp_ratio * cfg.embed_dim,
                moe=MoEConfig(
                    num_experts=cfg.num_experts,
                    num_experts_per_tok=cfg.num_experts_per_tok,
                    capacity_factor=cfg.moe_capacity_factor,
                ),
                dtype=cfg.dtype,
                name="moe_mlp",
            )(h)
            # Collected by the train step via mutable=["intermediates"]
            # (collect_moe_losses helper below).
            self.sow("intermediates", "moe_aux", aux)
        else:
            h = _dense(cfg.mlp_ratio * cfg.embed_dim, ("embed", "mlp"), cfg.dtype,
                       name="mlp_in")(h)
            h = nn.gelu(h)
            h = _dense(cfg.embed_dim, ("mlp", "embed"), cfg.dtype, name="mlp_out")(h)
        return x + h


class GPT(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        tokens,
        deterministic: bool = True,
        *,
        positions: Optional[jax.Array] = None,
        return_kv: bool = False,
        paged_caches: Optional[tuple] = None,
        paged_impl: str = "reference",
        paged_mesh: Optional[Any] = None,
    ):
        """Forward pass.

        Generation variants for ray_tpu.llm (same parameters, no fork):
          * ``return_kv=True`` (prefill): apply with
            ``mutable=["intermediates"]`` and read each layer's prompt K/V
            back via :func:`collect_kv_caches`.
          * ``paged_caches=(k_cache, v_cache, block_tables, context_lens)``
            or ``(..., k_scale, v_scale)`` (decode and prefix-aware partial
            prefill): k/v_cache are [L, num_blocks, block_size, H, D] paged
            pools (int8 pools carry [L, N, bs, H] scale tensors; pass None
            scales otherwise); tokens is [B, S] (S == 1 for decode, S > 1
            for the uncached suffix of a partially-cached prompt) and
            ``positions`` [B, S] must carry each token's absolute position.
            Attention reads the cached prefix through the block table and
            runs causally over the fed tokens — through the fused Pallas
            kernel when ``paged_impl="pallas"``, the XLA reference
            otherwise; the new K/V is sown for the caller to scatter into
            the cache. ``paged_mesh`` (a Mesh with a tp axis > 1) runs
            every attention head-sliced over the tensor-parallel axis —
            the serving engine passes its intra-replica mesh here so each
            chip's kernel instance only touches its local heads' cache.
        """
        cfg = self.config
        b, s = tokens.shape
        wte = nn.Embed(
            cfg.vocab_size,
            cfg.embed_dim,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            name="wte",
        )
        wpe = nn.Embed(
            cfg.max_seq_len,
            cfg.embed_dim,
            dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.01), (None, "embed")
            ),
            name="wpe",
        )
        if positions is None:
            positions = jnp.arange(s)[None, :]
        x = wte(tokens) + wpe(positions)
        if paged_caches is not None:
            if len(paged_caches) == 4:  # legacy: no scale tensors
                paged_caches = tuple(paged_caches) + (None, None)
            (k_cache, v_cache, block_tables, context_lens,
             k_scale, v_scale) = paged_caches
        for i in range(cfg.num_layers):
            use_moe = bool(
                cfg.num_experts and (i % cfg.moe_every == cfg.moe_every - 1)
            )
            paged_state = None
            if paged_caches is not None:
                paged_state = (
                    k_cache[i], v_cache[i], block_tables, context_lens,
                    None if k_scale is None else k_scale[i],
                    None if v_scale is None else v_scale[i],
                )
            x = Block(cfg, use_moe=use_moe, name=f"h_{i}")(
                x,
                deterministic=deterministic,
                return_kv=return_kv,
                paged_state=paged_state,
                paged_impl=paged_impl,
                paged_mesh=paged_mesh,
            )
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        # Tied LM head: logits via the embedding matrix. The matmul runs in
        # the model dtype (bf16 keeps the [S,E]x[E,V] head — ~27% of the
        # model's FLOPs — on the MXU fast path); the loss upcasts to f32
        # where the softmax needs it.
        logits = wte.attend(x)
        return logits


def cross_entropy_loss(logits, targets, mask: Optional[jax.Array] = None):
    """Token-level LM loss. logits [B,S,V], targets [B,S] int.

    Computed as logsumexp(logits) - logits[target] in f32: identical value
    to -log_softmax[target] but HBM-friendlier — XLA fuses the reduction
    instead of materializing a full [B,S,V] f32 log-probability tensor
    (1.6 GB at GPT-2 bench shapes), which dominated the loss's runtime."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tgt
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def logical_axis_rules(rules_table: dict) -> list[tuple[str, Any]]:
    """Convert a ray_tpu.parallel rules table into flax logical-axis rules
    (for nn.logical_to_mesh_sharding)."""
    return [(name, axis) for name, axis in rules_table.items()]


def collect_kv_caches(
    intermediates: Any, num_layers: int
) -> list[tuple[jax.Array, jax.Array]]:
    """Per-layer (k, v) sown by Blocks under `kv_cache`, in layer order.

    Pair with `model.apply(..., return_kv=True, mutable=["intermediates"])`
    (prefill) or a `paged_caches=` apply (decode / partial prefill): each
    entry is the K/V the layer computed for the *input* tokens —
    [B, S, H, D] of exactly the tokens fed, whose cache writes the caller
    owns ([B, 1, H, D] for a decode step)."""
    out = []
    for i in range(num_layers):
        entry = intermediates[f"h_{i}"]["kv_cache"]
        out.append(entry[0] if isinstance(entry, (tuple, list)) else entry)
    return out


def collect_moe_losses(intermediates: Any) -> jax.Array:
    """Sum MoE aux losses sown by Blocks: run `model.apply(params, tokens,
    mutable=["intermediates"])` and pass the returned collection here.
    Only `moe_aux` entries are summed — other sown diagnostics must never
    leak into the training objective."""

    def collect(node: Any, total: jax.Array) -> jax.Array:
        if isinstance(node, dict):
            for key, sub in node.items():
                if key == "moe_aux":
                    for leaf in jax.tree_util.tree_leaves(sub):
                        total = total + jnp.asarray(leaf, jnp.float32)
                else:
                    total = collect(sub, total)
        return total

    return collect(intermediates, jnp.zeros((), jnp.float32))
