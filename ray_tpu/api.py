"""Public core API (reference: python/ray/_private/worker.py — init :1186,
remote :3016, get :2506, put :2621, wait :2684, kill :2840, cancel :2870,
get_actor :2805)."""

from __future__ import annotations

import glob
import os
from typing import Any, Iterable, Optional, Sequence, Union

from ray_tpu._private import runtime as runtime_mod
from ray_tpu._private.engine import CONTEXT
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime import Runtime, get_runtime
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.remote_function import RemoteFunction


def _detect_num_tpu_chips() -> int:
    """Count local TPU chips without initializing JAX.

    Mirrors the accelerator-detection idea of the reference's resource probe
    (the reference counts GPUs for the `GPU` resource); TPU chips appear as
    /dev/accel* or /dev/vfio devices on TPU VMs. Explicit `num_tpus` or the
    RAY_TPU_CHIPS env var always wins.
    """
    env = os.environ.get("RAY_TPU_CHIPS")
    if env is not None:
        return int(env)
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return 0
    chips = len(glob.glob("/dev/accel*"))
    if chips:
        return chips
    # jax already imported and initialized? use it (cheap, no side effects).
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return sum(1 for d in jax.devices() if d.platform != "cpu")
        except Exception:
            return 0
    return 0


def init(
    *,
    address: Optional[str] = None,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    num_gpus: Optional[float] = None,
    resources: Optional[dict[str, float]] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    client_server_port: Optional[int] = None,
    _system_config: Optional[dict] = None,
) -> Runtime:
    """Start the runtime with one (head) node, or connect to a remote one.

    `address="host:port"` connects this process as a remote driver to a head
    started with `client_server_port=...` (the ray-client analog,
    reference: python/ray/util/client/) — the returned proxy serves the full
    task/actor/object API over the wire protocol.

    Unlike the reference the local case never spawns daemons — the control
    plane is in-process. Multi-node tests use ray_tpu.cluster_utils.Cluster
    to add logical nodes.
    """
    if runtime_mod._RUNTIME is not None:
        if ignore_reinit_error:
            return runtime_mod._RUNTIME
        raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
    if address is not None:
        ignored = {
            "num_cpus": num_cpus,
            "num_tpus": num_tpus,
            "num_gpus": num_gpus,
            "resources": resources,
            "client_server_port": client_server_port,
            "_system_config": _system_config,
        }
        bad = [k for k, v in ignored.items() if v is not None]
        if bad:
            raise ValueError(
                f"init(address=...) connects to an existing head; {bad} "
                "only apply when starting a local runtime"
            )
        from ray_tpu._private.client import connect

        proxy = connect(address, namespace=namespace)
        runtime_mod._RUNTIME = proxy
        return proxy
    node_resources = dict(resources or {})
    node_resources["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    tpus = float(num_tpus if num_tpus is not None else _detect_num_tpu_chips())
    if tpus:
        node_resources["TPU"] = tpus
    if num_gpus:
        node_resources["GPU"] = float(num_gpus)
    runtime = Runtime(
        resources=node_resources, system_config=_system_config, namespace=namespace
    )
    if client_server_port is not None:
        connect_address = runtime.serve_clients(port=client_server_port)
        # Surface the credentialed connect string — the auto-generated auth
        # token lives only in this address (or RAY_TPU_CLIENT_TOKEN on both
        # sides), so remote drivers have no other way to obtain it.
        print(f"ray_tpu client server listening; connect with "
              f'ray_tpu.init(address="{connect_address}")')
    return runtime


def is_initialized() -> bool:
    return runtime_mod._RUNTIME is not None


def shutdown() -> None:
    if runtime_mod._RUNTIME is not None:
        runtime_mod._RUNTIME.shutdown()


def remote(*args, **kwargs):
    """@remote decorator for functions and classes (worker.py:3016)."""

    def make(target, options):
        if isinstance(target, type):
            return ActorClass(target, options)
        if callable(target):
            return RemoteFunction(target, options)
        raise TypeError(f"@remote target must be a function or class, got {target!r}")

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return make(args[0], {})
    if args:
        raise TypeError("@remote only takes keyword options, e.g. @remote(num_cpus=2)")

    def decorator(target):
        return make(target, kwargs)

    return decorator


def put(value: Any) -> ObjectRef:
    return get_runtime().put(value)


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
):
    runtime = get_runtime()
    if isinstance(refs, ObjectRef):
        return runtime.get([refs], timeout)[0]
    if isinstance(refs, (list, tuple)):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(f"get() expects ObjectRefs, got {type(bad[0]).__name__}")
        return runtime.get(list(refs), timeout)
    raise TypeError(f"get() expects an ObjectRef or list, got {type(refs).__name__}")


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
) -> tuple[list[ObjectRef], list[ObjectRef]]:
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns cannot exceed the number of refs")
    return get_runtime().wait(refs, num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle; use cancel() for tasks")
    get_runtime().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    get_runtime().cancel(ref, force=force, recursive=recursive)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    runtime = get_runtime()
    actor_id = runtime.controller.get_named_actor(name, namespace or runtime.namespace)
    if actor_id is None:
        raise ValueError(f"Failed to look up actor with name {name!r}")
    record = runtime.controller.get_actor_record(actor_id)
    return ActorHandle(actor_id, record.class_name if record else "Actor")


class RuntimeContext:
    """reference: ray.runtime_context.RuntimeContext."""

    def __init__(self, runtime: Runtime):
        self._runtime = runtime

    def get_job_id(self) -> str:
        return self._runtime.job_id.hex()

    def get_task_id(self) -> Optional[str]:
        task_id = CONTEXT.task_id
        return task_id.hex() if task_id else None

    def get_actor_id(self) -> Optional[str]:
        actor_id = CONTEXT.actor_id
        return actor_id.hex() if actor_id else None

    def get_node_id(self) -> Optional[str]:
        node_id = CONTEXT.node_id or self._runtime.controller.head_node_id
        return node_id.hex() if node_id else None

    def get_assigned_resources(self) -> dict[str, float]:
        return dict(CONTEXT.resource_grant)


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(get_runtime())


def get_tpu_ids() -> list[int]:
    """Chip indices granted to the current task/actor (the TPU analog of
    ray.get_gpu_ids, _private/worker.py:916)."""
    grant = CONTEXT.resource_grant
    count = int(grant.get("TPU", 0)) if grant else 0
    for name in grant or {}:
        if name.startswith("TPU_group_"):
            count = max(count, int(grant[name]))
    return list(range(count))


def nodes() -> list[dict]:
    runtime = get_runtime()
    return [
        {
            "NodeID": n.node_id.hex(),
            "Alive": n.alive,
            "Resources": dict(n.total),
            "Available": dict(n.available),
            "Labels": dict(n.labels),
        }
        for n in runtime.controller.alive_nodes()
    ]


def cluster_resources() -> dict[str, float]:
    totals: dict[str, float] = {}
    for node in get_runtime().controller.alive_nodes():
        for name, amount in node.total.items():
            totals[name] = totals.get(name, 0.0) + amount
    return totals


def available_resources() -> dict[str, float]:
    totals: dict[str, float] = {}
    for node in get_runtime().controller.alive_nodes():
        for name, amount in node.available.items():
            totals[name] = totals.get(name, 0.0) + amount
    return totals


def timeline(
    filename: Optional[str] = None, trace_id: Optional[str] = None
):
    """Chrome-trace timeline of task executions AND buffered tracing spans
    (reference: ray.timeline, _private/state.py:831 backed by GCS profile
    events; here backed by the runtime's task-event buffer plus the span
    buffer, so `llm.*` serving and `train.*` training spans appear on the
    same timeline as their tasks). Returns the trace records, and writes
    them as JSON when `filename` is given — load in chrome://tracing or
    Perfetto.

    With `trace_id`, exports ONE request's connected timeline instead:
    a Perfetto trace object with per-actor process rows (handle →
    router → ingress → engine) and flow events stitching the
    cross-actor span ids (observability.perfetto)."""
    runtime = get_runtime()
    if trace_id is not None:
        from ray_tpu.observability.perfetto import (
            perfetto_trace,
            write_perfetto_trace,
        )

        if filename:
            return write_perfetto_trace(
                filename, trace_id=trace_id, runtime=runtime
            )
        return perfetto_trace(trace_id=trace_id, runtime=runtime)
    from ray_tpu.util import tracing

    events = runtime.task_events.chrome_trace() + tracing.chrome_spans(runtime)
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(events, f)
    return events
