"""In-process multi-node cluster fixture.

The keystone test asset (SURVEY.md §4): the reference's `cluster_utils.Cluster`
(python/ray/cluster_utils.py:99, add_node :165, remove_node :238) runs real
raylet+GCS process trees with fabricated resources; here nodes are logical
entries in the shared control plane with their own execution engine, which is
what the scheduling/spillback/PG/failure tests need.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private import runtime as runtime_mod
from ray_tpu._private.ids import NodeID
from ray_tpu._private.runtime import Runtime


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
    ):
        self._runtime: Optional[Runtime] = None
        self.head_node: Optional[NodeID] = None
        if initialize_head:
            args = head_node_args or {"num_cpus": 1}
            self._runtime = Runtime(resources=None)
            self.head_node = self.add_node(**args)

    @property
    def runtime(self) -> Runtime:
        assert self._runtime is not None
        return self._runtime

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        num_gpus: float = 0,
        resources: Optional[dict] = None,
        labels: Optional[dict] = None,
    ) -> NodeID:
        node_resources = dict(resources or {})
        if num_cpus:
            node_resources["CPU"] = float(num_cpus)
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        if num_gpus:
            node_resources["GPU"] = float(num_gpus)
        is_head = self.head_node is None
        node_id = self.runtime.add_node(node_resources, labels, is_head=is_head)
        if is_head:
            self.head_node = node_id
        return node_id

    def remove_node(self, node_id: NodeID) -> None:
        self.runtime.remove_node(node_id)

    def shutdown(self) -> None:
        if self._runtime is not None:
            self._runtime.shutdown()
            self._runtime = None
