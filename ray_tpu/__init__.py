"""ray_tpu — a TPU-native distributed ML framework.

A ground-up re-design of Ray (reference: /root/reference) for JAX/XLA on TPU:
the core task/actor/object API and control plane live here; the ML libraries
(train/tune/data/serve/rllib) are built purely on this public API, preserving
the reference's single most important layering rule (SURVEY.md §overview).
"""

import os as _os

# This image's pyarrow ships a jemalloc default memory pool that intermittently
# corrupts itself under heavy thread churn (reproducible: runtime shutdown's
# pool-thread exits followed by any arrow call segfaults in ~70% of runs;
# 0% with the system allocator). Must be set before pyarrow's first import —
# ray_tpu imports precede data use, so here.
_os.environ.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")

from ray_tpu import exceptions
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    get_tpu_ids,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.streaming import ObjectRefGenerator
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"

__all__ = [
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "ObjectRefGenerator",
    "RemoteFunction",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "get",
    "get_actor",
    "get_runtime_context",
    "get_tpu_ids",
    "init",
    "is_initialized",
    "kill",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "timeline",
    "wait",
]
