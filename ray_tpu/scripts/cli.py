"""`ray-tpu` CLI — status / state listing / jobs / timeline / bench.

Reference: python/ray/scripts/scripts.py (`ray status`, `ray list ...` via
util/state/state_cli.py, `ray job submit` via the job CLI, `ray timeline`).
The in-process runtime has no daemons to attach to, so every invocation
bootstraps a local runtime (configurable with --num-cpus), runs the command,
and shuts down — `job submit` still executes the entrypoint as a real
subprocess with logs and status.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


def _init(args) -> None:
    import ray_tpu

    # ignore_reinit_error: handlers are also driven in-process against an
    # already-running runtime (tests, embedding scripts); standalone CLI
    # invocations still bootstrap their own.
    ray_tpu.init(
        num_cpus=getattr(args, "num_cpus", None) or 8,
        ignore_reinit_error=True,
    )


def cmd_status(args) -> int:
    import ray_tpu

    _init(args)
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    nodes = ray_tpu.nodes()
    print(f"Nodes: {len(nodes)}")
    print("Resources:")
    for name in sorted(total):
        print(f"  {name}: {avail.get(name, 0.0):g}/{total[name]:g} available")
    ray_tpu.shutdown()
    return 0


def cmd_list(args) -> int:
    from ray_tpu.util import state as state_api

    _init(args)
    fn = {
        "tasks": state_api.list_tasks,
        "actors": state_api.list_actors,
        "nodes": state_api.list_nodes,
        "objects": state_api.list_objects,
        "placement-groups": state_api.list_placement_groups,
    }[args.what]
    rows = fn()
    print(json.dumps(rows, indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    from ray_tpu.util.state import summarize_actors, summarize_tasks

    _init(args)
    print(
        json.dumps(
            {"tasks": summarize_tasks(), "actors": summarize_actors()},
            indent=2,
        )
    )
    return 0


def cmd_train_stats(args) -> int:
    """Training telemetry: recent fit() runs with per-phase breakdowns and
    straggler flags. With --url, queries a running head's dashboard
    /api/train (the persistent-cluster path); without, reads this
    process's run registry (fresh CLI runtimes have none — useful mainly
    from scripts that just ran a trainer in-process)."""
    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + f"/api/train?rounds={args.rounds}"
        with urllib.request.urlopen(url, timeout=10) as resp:
            runs = json.loads(resp.read().decode())
    else:
        # The run registry is process-local: no runtime needed to read it.
        from ray_tpu.train.observability import list_runs

        runs = list_runs(rounds_limit=args.rounds)
    print(json.dumps(runs, indent=2, default=str))
    return 0


_LEDGER_COLS = (
    "idle_s", "prefill_s", "fabric_wait_s", "host_schedule_s",
    "device_s", "commit_s", "other_s", "loop_s",
)


def _print_fleet(snap: dict) -> None:
    replicas = snap.get("replicas") or {}
    if not replicas:
        print("no live llm engines")
        return
    short = [c[:-2] for c in _LEDGER_COLS]  # strip the _s suffix
    header = (
        f"{'replica':<28} {'wall':>8} "
        + " ".join(f"{c:>9}" for c in short)
        + f" {'sum/wall':>8} {'tok/s':>8} {'mfu':>6}"
    )
    print(header)
    for name, row in sorted(replicas.items()):
        if "error" in row:
            print(f"{name:<28} error: {row['error']}")
            continue
        ledger = row["ledger"]
        fr = ledger.get("fractions") or {}
        pct = lambda x: f"{100 * x:8.1f}%" if x is not None else "       —"
        cells = " ".join(pct(fr.get(c)) for c in _LEDGER_COLS)
        cov = ledger.get("coverage")
        mfu = ledger.get("mfu")
        print(
            f"{name:<28} {ledger['wall_s']:7.2f}s {cells}"
            f" {pct(cov)} {ledger['goodput_tokens_per_s']:8.1f}"
            f" {('%5.1f%%' % (100 * mfu)) if mfu is not None else '    —'}"
        )
    fleet = snap.get("fleet") or {}
    tops = ", ".join((fleet.get("bottlenecks") or [])[:3]) or "—"
    print(
        f"fleet: {fleet.get('replicas', 0)} replicas · "
        f"{fleet.get('goodput_tokens_per_s', 0.0):.1f} tok/s · "
        f"top columns: {tops}"
    )
    for metric, p in (snap.get("percentiles") or {}).items():
        p50 = p.get("p50")
        p99 = p.get("p99")
        fmt = lambda v: f"{1e3 * v:.1f}ms" if v is not None else "—"
        print(f"  {metric}: p50 {fmt(p50)} p99 {fmt(p99)} (n={p['count']})")


def cmd_top(args) -> int:
    """Fleet time ledger: where every replica's wall time went
    (host-schedule / device / commit / fabric-wait / idle / loop), with
    goodput and MFU. With --url, polls a running head's dashboard
    /api/fleet; without, scrapes this process's runtime directly (useful
    from scripts that just served in-process)."""
    import time as _time

    def _fetch() -> dict:
        if args.url:
            import urllib.request

            url = args.url.rstrip("/") + f"/api/fleet?steps={args.steps}"
            with urllib.request.urlopen(url, timeout=10) as resp:
                return json.loads(resp.read().decode())
        from ray_tpu.observability import fleet_snapshot

        return fleet_snapshot(steps_limit=args.steps)

    if not args.url:
        _init(args)
    try:
        while True:
            snap = _fetch()
            if args.json:
                print(json.dumps(snap, indent=2, default=str))
            else:
                _print_fleet(snap)
            if not args.watch:
                break
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_timeline(args) -> int:
    import ray_tpu

    _init(args)
    trace_id = getattr(args, "trace_id", None)
    out = ray_tpu.timeline(args.output, trace_id=trace_id)
    if trace_id is not None:
        n = len(out.get("traceEvents", []))
        print(
            f"Wrote {n} trace events for trace {trace_id} to "
            f"{args.output} (load at https://ui.perfetto.dev)"
        )
    else:
        print(f"Wrote {len(out)} trace events to {args.output}")
    return 0


def cmd_job(args) -> int:
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    _init(args)
    client = JobSubmissionClient()
    if args.job_cmd == "submit":
        import shlex

        parts = list(args.entrypoint)
        if parts and parts[0] == "--":
            parts = parts[1:]
        # shlex.join keeps arguments with spaces (python -c "...") intact
        # through the supervisor's shell.
        entrypoint = shlex.join(parts)
        env = {"env_vars": dict(kv.split("=", 1) for kv in args.env or [])}
        job_id = client.submit_job(entrypoint=entrypoint, runtime_env=env)
        print(f"Submitted {job_id}")
        # The runtime (and its job table) lives only as long as this process,
        # so the CLI always waits for the entrypoint (no --no-wait / list:
        # those need a persistent cluster to attach to).
        status = client.wait_until_finish(job_id, timeout=args.timeout)
        print(f"Status: {status}")
        sys.stdout.write(client.get_job_logs(job_id))
        ray_tpu.shutdown()
        return 0 if status == "SUCCEEDED" else 1
    raise SystemExit(f"unknown job command {args.job_cmd!r}")


def cmd_metrics(args) -> int:
    from ray_tpu.util.metrics import prometheus_text

    _init(args)
    sys.stdout.write(prometheus_text())
    return 0


def cmd_logs(args) -> int:
    """Tail aggregated worker logs (reference: `ray logs` +
    log_monitor-fed dashboard log view). With --address, queries a running
    head over the client protocol; without, there is no persistent cluster
    to read from, so --address is required."""
    import time as _time

    import ray_tpu
    from ray_tpu._private.runtime import get_runtime

    ray_tpu.init(address=args.address)
    runtime = get_runtime()
    # This command polls get_logs itself; pushed batches would double-print.
    runtime._client_core.print_pushed_logs = False
    after = 0
    try:
        while True:
            reply = runtime._client_core.rpc(
                "get_logs",
                {
                    "node_id": args.node_id,
                    "wid": args.wid,
                    "after_seq": after,
                    "limit": 1000,
                },
            )
            rows = reply["rows"]
            for row in rows:
                after = max(after, row["seq"])
                print(
                    f"(wid={row['wid']} pid={row['pid']}, "
                    f"node={row['hostname']}) [{row['stream']}] {row['line']}"
                )
            if not args.follow:
                break
            _time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    ray_tpu.shutdown()
    return 0


def cmd_dashboard(args) -> int:
    """Serve the web dashboard for a local demo runtime (when a head runs
    in-process, init(include_dashboard=True) serves it from the head
    itself)."""
    import time as _time

    import ray_tpu
    from ray_tpu._private.runtime import get_runtime

    ray_tpu.init(
        num_cpus=getattr(args, "num_cpus", None) or 8,
        _system_config={
            "include_dashboard": True,
            "dashboard_port": args.port,
            "dashboard_host": args.host,
        },
    )
    print(f"Dashboard at {get_runtime().dashboard.url} (Ctrl-C to stop)")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        pass
    ray_tpu.shutdown()
    return 0


def cmd_start(args) -> int:
    """Join an existing head as a worker node (`ray start --address=...`,
    reference: services.py:1353 start_raylet). Blocks until the head goes
    away; the daemon fate-shares with its connection."""
    from ray_tpu._private import node_daemon

    daemon_args = ["--address", args.address]
    if args.num_cpus is not None:
        daemon_args += ["--num-cpus", str(args.num_cpus)]
    if args.num_gpus is not None:
        daemon_args += ["--num-gpus", str(args.num_gpus)]
    if args.num_tpus is not None:
        daemon_args += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        daemon_args += ["--resources", args.resources]
    if args.labels:
        daemon_args += ["--labels", args.labels]
    if args.object_store_memory:
        daemon_args += ["--object-store-memory", str(args.object_store_memory)]
    node_daemon.main(daemon_args)
    return 0


def _forward_lint(rest: list) -> int:
    """Hand everything after `lint` to the analyzer's own parser. Pure
    AST pass — never boots a runtime. See ray_tpu/tools/lint and the
    README "Static analysis" section."""
    from ray_tpu.tools.lint.cli import main as lint_main

    rest = list(rest)
    if rest and rest[0] == "--":
        rest = rest[1:]
    return lint_main(rest)


def cmd_lint(args) -> int:
    return _forward_lint(args.lint_args)


def _forward_loadgen(rest: list) -> int:
    """Hand everything after `loadgen` to the traffic harness's own
    parser (ray_tpu/loadgen/sweep.py): `run` one scenario/rate cell,
    `sweep` the knob space into a BENCH_SERVE record, `report` an
    existing record. The harness boots its own runtime."""
    from ray_tpu.loadgen.sweep import main as loadgen_main

    rest = list(rest)
    if rest and rest[0] == "--":
        rest = rest[1:]
    return loadgen_main(rest)


def cmd_loadgen(args) -> int:
    return _forward_loadgen(args.loadgen_args)


def main(argv: Optional[list] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "lint":
        # Forward verbatim when `lint` leads: the analyzer owns its flags
        # (`ray-tpu lint --json` must not be eaten by this parser —
        # argparse.REMAINDER only engages after a positional). With global
        # flags before the subcommand, argparse dispatches to cmd_lint.
        return _forward_lint(argv[1:])
    if argv and argv[0] == "loadgen":
        # Same verbatim-forward contract as lint: the harness owns its
        # flags (`ray-tpu loadgen sweep --quick` must reach its parser).
        return _forward_loadgen(argv[1:])
    parser = argparse.ArgumentParser(
        prog="ray-tpu", description="TPU-native distributed ML framework CLI"
    )
    parser.add_argument("--num-cpus", type=int, default=None)
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status", help="cluster resources")

    p_list = sub.add_parser("list", help="list cluster state")
    p_list.add_argument(
        "what",
        choices=["tasks", "actors", "nodes", "objects", "placement-groups"],
    )

    sub.add_parser("summary", help="task + actor summaries by name:state")

    p_ts = sub.add_parser(
        "train-stats", help="recent training runs: rounds, phases, stragglers"
    )
    p_ts.add_argument(
        "--url", default=None, help="dashboard base URL of a running head"
    )
    p_ts.add_argument("--rounds", type=int, default=8)

    p_tl = sub.add_parser("timeline", help="export chrome trace")
    p_tl.add_argument("--output", default="timeline.json")
    p_tl.add_argument(
        "--trace-id",
        default=None,
        help="export ONE request's connected Perfetto timeline "
        "(per-actor rows + flow events) instead of the cluster trace",
    )

    p_top = sub.add_parser(
        "top", help="fleet time ledger: wall-time breakdown per replica"
    )
    p_top.add_argument(
        "--url", default=None, help="dashboard base URL of a running head"
    )
    p_top.add_argument("--steps", type=int, default=512)
    p_top.add_argument("--json", action="store_true")
    p_top.add_argument(
        "--watch", action="store_true", help="refresh continuously"
    )
    p_top.add_argument("--interval", type=float, default=2.0)

    p_job = sub.add_parser("job", help="job submission")
    job_sub = p_job.add_subparsers(dest="job_cmd", required=True)
    p_submit = job_sub.add_parser("submit")
    p_submit.add_argument("--env", action="append", help="KEY=VALUE", default=None)
    p_submit.add_argument("--timeout", type=float, default=3600.0)
    p_submit.add_argument("entrypoint", nargs=argparse.REMAINDER)

    sub.add_parser("metrics", help="prometheus exposition dump")

    p_lint = sub.add_parser(
        "lint",
        help="static analysis: races, async deadlocks, jit trace-safety",
    )
    p_lint.add_argument(
        "lint_args",
        nargs=argparse.REMAINDER,
        help="paths and flags forwarded to the analyzer "
        "(--rule ID, --json, --baseline FILE, --write-baseline, "
        "--list-rules)",
    )

    p_lg = sub.add_parser(
        "loadgen",
        help="open-loop serving load generator: run / sweep / report",
    )
    p_lg.add_argument(
        "loadgen_args",
        nargs=argparse.REMAINDER,
        help="subcommand and flags forwarded to the harness "
        "(run --rate ..., sweep --quick, report FILE)",
    )

    p_logs = sub.add_parser("logs", help="tail aggregated worker logs")
    p_logs.add_argument(
        "--address", required=True, help="head connect string host:port?token=..."
    )
    p_logs.add_argument("--node-id", default=None)
    p_logs.add_argument("--wid", type=int, default=None)
    p_logs.add_argument("--follow", "-f", action="store_true")

    p_dash = sub.add_parser("dashboard", help="serve the web dashboard")
    p_dash.add_argument("--port", type=int, default=8265)
    p_dash.add_argument("--host", default="127.0.0.1")

    p_start = sub.add_parser(
        "start", help="join a head as a worker node (node daemon)"
    )
    p_start.add_argument(
        "--address", required=True, help="head connect string host:port?token=..."
    )
    p_start.add_argument("--num-cpus", type=float, default=None)
    p_start.add_argument("--num-gpus", type=float, default=None)
    p_start.add_argument("--num-tpus", type=float, default=None)
    p_start.add_argument("--resources", default=None, help="extra resources JSON")
    p_start.add_argument("--labels", default=None, help="node labels JSON")
    p_start.add_argument("--object-store-memory", type=int, default=None)

    args = parser.parse_args(argv)
    handler = {
        "status": cmd_status,
        "list": cmd_list,
        "summary": cmd_summary,
        "train-stats": cmd_train_stats,
        "timeline": cmd_timeline,
        "top": cmd_top,
        "job": cmd_job,
        "metrics": cmd_metrics,
        "lint": cmd_lint,
        "loadgen": cmd_loadgen,
        "start": cmd_start,
        "logs": cmd_logs,
        "dashboard": cmd_dashboard,
    }[args.cmd]
    return handler(args)


if __name__ == "__main__":
    raise SystemExit(main())
