"""Searcher protocol (reference: tune/search/searcher.py Searcher ABC,
tune/search/basic_variant.py BasicVariantGenerator,
tune/search/concurrency_limiter.py).

A Searcher suggests configs and learns from completed-trial results; the
grid/random default just walks the variant generator. Bayesian-style adapters
(Optuna/HyperOpt/...) plug in by subclassing `Searcher` — the controller only
sees suggest/on_trial_complete.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.search.variant_generator import generate_variants


class Searcher:
    """Suggest-based search algorithm interface."""

    FINISHED = "FINISHED"

    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(
        self, metric: Optional[str], mode: Optional[str], config: dict
    ) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(
        self, trial_id: str, result: Optional[dict] = None, error: bool = False
    ) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid + random search over the param_space (the default searcher)."""

    def __init__(
        self,
        space: Optional[dict] = None,
        num_samples: int = 1,
        seed: Optional[int] = None,
        max_concurrent: int = 0,
    ):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._seed = seed
        self._iter = None
        self.max_concurrent = max_concurrent

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config:
            self._space = config
        return True

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._iter is None:
            self._iter = generate_variants(
                self._space, self._num_samples, self._seed
            )
        try:
            return next(self._iter)
        except StopIteration:
            return None

    @property
    def total_samples(self) -> int:
        from ray_tpu.tune.search.variant_generator import count_variants

        return count_variants(self._space, self._num_samples)


class RandomSearch(Searcher):
    """Pure random sampling forever (bounded by num_samples at the Tuner)."""

    def __init__(self, space: dict, seed: Optional[int] = None):
        super().__init__()
        self._space = space
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Optional[dict]:
        return next(generate_variants(self._space, 1, self._rng.random()))


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions from a wrapped searcher."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        return self.searcher.set_search_properties(metric, mode, config)

    def is_saturated(self) -> bool:
        return len(self._live) >= self.max_concurrent

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self.is_saturated():
            return None  # backpressure: controller checks is_saturated()
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self._live.add(trial_id)
        return config

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
