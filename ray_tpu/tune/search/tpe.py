"""Native Tree-structured Parzen Estimator searcher.

The model-based searcher the reference reaches through adapters
(tune/search/optuna/optuna_search.py wraps Optuna, whose default sampler is
TPE; tune/search/hyperopt/ wraps Hyperopt's original implementation). The
image is sealed — no optuna/hyperopt — so this is the algorithm itself,
implemented against the same Searcher ABC the adapters use:

  * completed trials split into good (top `gamma` quantile) and bad sets;
  * per dimension, good/bad observations fit kernel densities (Gaussian
    KDE in the domain's transformed space for continuous dims; smoothed
    categoricals for Choice/Randint);
  * `n_candidates` configs sampled from the good model are scored by the
    summed per-dimension log-likelihood ratio l(x|good) - l(x|bad); the
    argmax is suggested (expected-improvement-proportional, per Bergstra
    et al. 2011 — PAPERS.md).

Plain non-Domain values in the space pass through untouched.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search.sample import (
    Choice,
    Domain,
    LogUniform,
    Normal,
    QNormal,
    QUniform,
    Randint,
    Uniform,
)
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.variant_generator import generate_variants

_CONTINUOUS = (Uniform, LogUniform, QUniform, Normal, QNormal)


class _ContinuousDim:
    """Gaussian-KDE model of one continuous dimension (log-transformed for
    LogUniform domains; large Randint ranges model continuously with
    integer rounding — enumerating them would blow up memory)."""

    def __init__(self, domain: Domain):
        self.domain = domain
        self.log = isinstance(domain, LogUniform)
        self.integer = isinstance(domain, Randint)
        lo = getattr(domain, "lower", None)
        hi = getattr(domain, "upper", None)
        if self.integer and hi is not None:
            hi = hi - 1  # Randint upper bound is exclusive
        self.lo = self._tf(lo) if lo is not None else None
        self.hi = self._tf(hi) if hi is not None else None

    def _tf(self, x: float) -> float:
        return math.log(x) if self.log else x

    def _inv(self, x: float) -> float:
        return math.exp(x) if self.log else x

    def _bandwidth(self, obs: List[float]) -> float:
        if len(obs) < 2:
            span = (
                (self.hi - self.lo)
                if self.lo is not None and self.hi is not None
                else 1.0
            )
            return max(1e-6, 0.25 * span)
        mean = sum(obs) / len(obs)
        var = sum((x - mean) ** 2 for x in obs) / (len(obs) - 1)
        sigma = math.sqrt(max(var, 1e-12))
        bw = 1.06 * sigma * len(obs) ** -0.2  # Silverman's rule
        if self.lo is not None and self.hi is not None:
            bw = max(bw, (self.hi - self.lo) / 20.0)
        return max(bw, 1e-6)

    def sample(self, obs: List[float], rng: random.Random) -> float:
        # The good model is a mixture of the observation kernels AND the
        # uniform prior weighted as one pseudo-observation (Bergstra et
        # al.'s prior-smoothed Parzen estimator): without the prior the
        # search collapses onto the best startup point and never explores.
        if not obs or rng.random() < 1.0 / (len(obs) + 1):
            # Unbounded domains (Normal/QNormal) use the domain itself as
            # the prior; bounded ones the uniform span.
            if self.lo is None or self.hi is None:
                return self.domain.sample(rng)
            x = rng.uniform(self.lo, self.hi)
        else:
            bw = self._bandwidth(obs)
            center = rng.choice(obs)
            x = rng.gauss(center, bw)
            if self.lo is not None:
                x = min(max(x, self.lo), self.hi)
        # Q-domains keep their quantization on the way out.
        value = self._inv(x)
        q = getattr(self.domain, "q", None)
        if q:
            value = round(value / q) * q
        if self.integer:
            value = int(round(value))
        return value

    def log_density(self, value: float, obs: List[float]) -> float:
        x = self._tf(max(value, 1e-300) if self.log else value)
        span = (
            max(self.hi - self.lo, 1e-12)
            if self.lo is not None and self.hi is not None
            else None
        )
        if not obs:
            return -math.log(span) if span else 0.0
        bw = self._bandwidth(obs)
        acc = 0.0
        for center in obs:
            z = (x - center) / bw
            acc += math.exp(-0.5 * z * z)
        kde = acc / (len(obs) * bw * math.sqrt(2 * math.pi))
        # Same prior mixture as sample(): 1 pseudo-observation of uniform.
        w = 1.0 / (len(obs) + 1)
        dens = (1.0 - w) * kde + (w / span if span else 0.0)
        return math.log(max(dens, 1e-300))


class _CategoricalDim:
    """Smoothed-count model for Choice / Randint dimensions."""

    def __init__(self, domain: Domain):
        if isinstance(domain, Choice):
            self.values = list(domain.categories)
        else:  # Randint
            self.values = list(range(domain.lower, domain.upper))
        self.k = max(len(self.values), 1)

    def _probs(self, obs: List[Any]) -> Dict[Any, float]:
        prior = 1.0
        counts = {v: prior for v in self.values}
        for x in obs:
            if x in counts:
                counts[x] += 1.0
        total = sum(counts.values())
        return {v: c / total for v, c in counts.items()}

    def sample(self, obs: List[Any], rng: random.Random) -> Any:
        probs = self._probs(obs)
        r = rng.random()
        acc = 0.0
        for v, p in probs.items():
            acc += p
            if r <= acc:
                return v
        return self.values[-1]

    def log_density(self, value: Any, obs: List[Any]) -> float:
        return math.log(self._probs(obs).get(value, 1e-12))


class TPESearch(Searcher):
    """Model-based suggest: random for `n_startup_trials`, then TPE."""

    def __init__(
        self,
        space: dict,
        metric: Optional[str] = None,
        mode: str = "max",
        n_startup_trials: int = 10,
        gamma: float = 0.15,
        n_candidates: int = 24,
        seed: Optional[int] = None,
    ):
        super().__init__(metric, mode)
        self._space = space
        self._n_startup = n_startup_trials
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._dims: Dict[str, Any] = {}
        for key, domain in space.items():
            if isinstance(domain, _CONTINUOUS):
                self._dims[key] = _ContinuousDim(domain)
            elif isinstance(domain, Randint):
                # Small integer ranges are categorical counts; large ones
                # would enumerate billions of values — model continuously.
                if domain.upper - domain.lower <= 64:
                    self._dims[key] = _CategoricalDim(domain)
                else:
                    self._dims[key] = _ContinuousDim(domain)
            elif isinstance(domain, Choice):
                self._dims[key] = _CategoricalDim(domain)
        # trial_id -> config for pending trials; (config, score) history.
        self._pending: Dict[str, dict] = {}
        self._history: List[tuple] = []

    # -- Searcher interface -------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._history) < self._n_startup or not self._dims:
            config = next(
                generate_variants(self._space, 1, self._rng.random())
            )
        else:
            config = self._suggest_tpe()
        self._pending[trial_id] = config
        return config

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        config = self._pending.pop(trial_id, None)
        if config is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._history.append((config, score))

    # -- TPE core -----------------------------------------------------------

    def _split(self):
        ranked = sorted(self._history, key=lambda cs: cs[1], reverse=True)
        n_good = max(1, int(math.ceil(self._gamma * len(ranked))))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        return good, bad

    def _suggest_tpe(self) -> dict:
        good, bad = self._split()
        return tpe_best_candidate(
            self._space, self._dims, good, bad, self._n_candidates, self._rng
        )


def tpe_best_candidate(
    space: dict,
    dims: Dict[str, Any],
    good: List[dict],
    bad: List[dict],
    n_candidates: int,
    rng: random.Random,
) -> dict:
    """The TPE proposal step shared by TPESearch and TuneBOHB: sample
    `n_candidates` configs from the good-set kernel densities and return the
    one maximizing the summed log-likelihood ratio l(x|good) - l(x|bad)."""
    obs_good = {key: [c[key] for c in good if key in c] for key in dims}
    obs_bad = {key: [c[key] for c in bad if key in c] for key in dims}
    for key, dim in dims.items():
        if isinstance(dim, _ContinuousDim):
            obs_good[key] = [dim._tf(v) for v in obs_good[key]]
            obs_bad[key] = [dim._tf(v) for v in obs_bad[key]]

    best_config, best_score = None, -math.inf
    for _ in range(n_candidates):
        candidate = next(generate_variants(space, 1, rng.random()))
        score = 0.0
        for key, dim in dims.items():
            value = dim.sample(obs_good[key], rng)
            candidate[key] = value
            score += dim.log_density(value, obs_good[key])
            score -= dim.log_density(value, obs_bad[key])
        if score > best_score:
            best_config, best_score = candidate, score
    return best_config
