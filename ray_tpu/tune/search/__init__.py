"""Search algorithms."""
from ray_tpu.tune.search.sample import *  # noqa
from ray_tpu.tune.search.searcher import BasicVariantGenerator, ConcurrencyLimiter, RandomSearch, Searcher  # noqa
from ray_tpu.tune.search.tpe import TPESearch  # noqa
