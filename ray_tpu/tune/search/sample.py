"""Search-space domains (reference: python/ray/tune/search/sample.py).

Declarative distributions placed in `param_space`; `BasicVariantGenerator`
resolves them per trial. `grid_search` is a marker expanded into the cartesian
product across all grid entries (reference: tune/search/variant_generator.py).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    # PBT-style perturbation support: continuous domains can rescale.
    def perturb(self, value: Any, rng: random.Random) -> Any:
        return self.sample(rng)


@dataclass
class Uniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)

    def perturb(self, value, rng):
        factor = 1.2 if rng.random() > 0.5 else 0.8
        return min(self.upper, max(self.lower, value * factor))


@dataclass
class LogUniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))

    def perturb(self, value, rng):
        factor = 1.2 if rng.random() > 0.5 else 0.8
        return min(self.upper, max(self.lower, value * factor))


@dataclass
class Randint(Domain):
    lower: int
    upper: int  # exclusive

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)

    def perturb(self, value, rng):
        factor = 1.2 if rng.random() > 0.5 else 0.8
        return min(self.upper - 1, max(self.lower, int(value * factor)))


@dataclass
class Choice(Domain):
    categories: Sequence[Any]

    def sample(self, rng):
        return rng.choice(list(self.categories))


@dataclass
class QUniform(Domain):
    lower: float
    upper: float
    q: float

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return round(round(v / self.q) * self.q, 10)


@dataclass
class Normal(Domain):
    mean: float
    sd: float

    def sample(self, rng):
        return rng.normalvariate(self.mean, self.sd)


@dataclass
class QNormal(Domain):
    mean: float
    sd: float
    q: float

    def sample(self, rng):
        v = rng.normalvariate(self.mean, self.sd)
        return round(round(v / self.q) * self.q, 10)


@dataclass
class Function(Domain):
    """sample_from: arbitrary callable, optionally taking the spec/config."""

    fn: Callable

    def sample(self, rng):
        try:
            return self.fn()
        except TypeError:
            return self.fn(None)


@dataclass
class GridSearch:
    """Marker expanded to one variant per value (not a sampled Domain)."""

    values: Sequence[Any]


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> Randint:
    return Randint(lower, upper)


def choice(categories: Sequence[Any]) -> Choice:
    return Choice(categories)


def quniform(lower: float, upper: float, q: float) -> QUniform:
    return QUniform(lower, upper, q)


def qrandn(mean: float, sd: float, q: float) -> QNormal:
    return QNormal(mean, sd, q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> dict:
    """Reference spells grid search as {"grid_search": [...]}; keep that shape
    so user configs are drop-in compatible."""
    return {"grid_search": list(values)}
