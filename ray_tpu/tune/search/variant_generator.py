"""Expand a param_space into concrete trial configs.

Reference: tune/search/variant_generator.py — grid entries multiply
(cartesian product), Domain entries are sampled once per generated variant,
and the whole space is repeated `num_samples` times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Iterator

from ray_tpu.tune.search.sample import Domain, GridSearch


def _find_special(space: Any, path: tuple = ()):
    """Yield (path, entry) for every grid/domain node in a nested dict."""
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            yield path, GridSearch(space["grid_search"])
            return
        for k, v in space.items():
            yield from _find_special(v, path + (k,))
    elif isinstance(space, (GridSearch, Domain)):
        yield path, space


def _set_path(config: dict, path: tuple, value: Any) -> None:
    node = config
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value


def _deep_copy_resolved(space: Any) -> Any:
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            return None  # placeholder, filled by _set_path
        return {k: _deep_copy_resolved(v) for k, v in space.items()}
    if isinstance(space, (GridSearch, Domain)):
        return None
    if isinstance(space, list):
        return list(space)
    return space


def count_variants(space: dict, num_samples: int = 1) -> int:
    grids = [e for _, e in _find_special(space) if isinstance(e, GridSearch)]
    n = 1
    for g in grids:
        n *= len(g.values)
    return n * num_samples


def generate_variants(
    space: dict, num_samples: int = 1, seed: int | None = None
) -> Iterator[dict]:
    """Yield fully-resolved config dicts."""
    rng = random.Random(seed)
    specials = list(_find_special(space))
    grid_items = [(p, e) for p, e in specials if isinstance(e, GridSearch)]
    domain_items = [(p, e) for p, e in specials if isinstance(e, Domain)]

    grid_axes = [list(e.values) for _, e in grid_items] or [[None]]
    for _ in range(num_samples):
        for combo in itertools.product(*grid_axes):
            config = _deep_copy_resolved(space)
            if grid_items:
                for (path, _), value in zip(grid_items, combo):
                    _set_path(config, path, value)
            for path, domain in domain_items:
                _set_path(config, path, domain.sample(rng))
            yield config
