"""Native BOHB searcher: multi-fidelity TPE (Falkner et al. 2018).

The reference reaches BOHB through an adapter over HpBandSter
(tune/search/bohb/bohb_search.py, ConfigSpace-based KDEs) paired with the
HyperBandForBOHB scheduler (tune/schedulers/hb_bohb.py). The image is sealed
— no hpbandster/ConfigSpace — so this is the algorithm itself on the same
Searcher ABC, reusing the native TPE kernel-density machinery (tpe.py):

  * observations are bucketed by RUNG BUDGET (the HyperBand milestones of
    the paired scheduler: max_t * eta^-k);
  * suggest() builds the TPE good/bad split from the HIGHEST budget that has
    enough observations — BOHB's core idea: model the most informative
    fidelity available, fall back toward cheaper fidelities, and to random
    sampling before any rung has data;
  * a `random_fraction` of suggestions stays random regardless (the BOHB
    paper's guard against model collapse).

Pair with `ray_tpu.tune.schedulers.HyperBandForBOHB`, which fills brackets
sequentially so rung cohorts are budget-comparable.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.sample import Choice, Randint
from ray_tpu.tune.search.tpe import (
    _CONTINUOUS,
    _CategoricalDim,
    _ContinuousDim,
    tpe_best_candidate,
)
from ray_tpu.tune.search.variant_generator import generate_variants


class TuneBOHB(Searcher):
    def __init__(
        self,
        space: dict,
        metric: Optional[str] = None,
        mode: str = "max",
        max_t: int = 81,
        reduction_factor: float = 3,
        time_attr: str = "training_iteration",
        min_points_in_model: Optional[int] = None,
        gamma: float = 0.25,
        n_candidates: int = 24,
        random_fraction: float = 1.0 / 3.0,
        seed: Optional[int] = None,
    ):
        super().__init__(metric, mode)
        self._space = space
        self.time_attr = time_attr
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._random_fraction = random_fraction
        self._rng = random.Random(seed)
        self._dims: Dict[str, object] = {}
        for key, domain in space.items():
            if isinstance(domain, _CONTINUOUS):
                self._dims[key] = _ContinuousDim(domain)
            elif isinstance(domain, Randint):
                if domain.upper - domain.lower <= 64:
                    self._dims[key] = _CategoricalDim(domain)
                else:
                    self._dims[key] = _ContinuousDim(domain)
            elif isinstance(domain, Choice):
                self._dims[key] = _CategoricalDim(domain)
        # A model needs more points than dimensions to beat random (BOHB
        # paper's default: d+1, plus margin for the good/bad split).
        self._min_points = min_points_in_model or (len(self._dims) + 2)
        # Rung budgets of the paired HyperBand scheduler.
        milestones: List[int] = []
        t = max_t
        while t >= 1:
            milestones.append(int(t))
            t = t / reduction_factor
            if int(t) in milestones:
                break
        self._milestones = sorted(set(milestones))
        # budget -> [(config, score)]; (trial_id, budget) dedups recording.
        self._obs: Dict[int, List[tuple]] = {m: [] for m in self._milestones}
        self._recorded: set = set()
        self._pending: Dict[str, dict] = {}

    # -- Searcher interface -------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[dict]:
        config = self._suggest_config()
        self._pending[trial_id] = config
        return config

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        """Record the trial's score at every rung budget it has crossed —
        the multi-fidelity observations the per-budget models train on."""
        if self.metric not in result:
            return
        config = self._pending.get(trial_id)
        if config is None:
            return
        budget = result.get(self.time_attr, 0)
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        for milestone in self._milestones:
            if budget >= milestone and (trial_id, milestone) not in self._recorded:
                self._recorded.add((trial_id, milestone))
                self._obs[milestone].append((config, score))

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        if result is not None and not error:
            self.on_trial_result(trial_id, result)
        self._pending.pop(trial_id, None)

    # -- BOHB core ----------------------------------------------------------

    def _model_budget(self) -> Optional[int]:
        """Highest rung with enough observations to fit the TPE split."""
        for milestone in sorted(self._milestones, reverse=True):
            if len(self._obs[milestone]) >= self._min_points:
                return milestone
        return None

    def _suggest_config(self) -> dict:
        budget = self._model_budget()
        if (
            budget is None
            or not self._dims
            or self._rng.random() < self._random_fraction
        ):
            return next(generate_variants(self._space, 1, self._rng.random()))
        history = self._obs[budget]
        ranked = sorted(history, key=lambda cs: cs[1], reverse=True)
        n_good = max(1, int(math.ceil(self._gamma * len(ranked))))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        return tpe_best_candidate(
            self._space, self._dims, good, bad, self._n_candidates, self._rng
        )
