"""ray_tpu.tune — hyperparameter search (reference: python/ray/tune/).

Built purely on the public task/actor API, like the reference: the controller
is an event loop over trial actors (tune/execution/tune_controller.py:49).
"""

from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    DistributeResources,
    FIFOScheduler,
    HyperBandForBOHB,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search.sample import (
    choice,
    grid_search,
    loguniform,
    qrandn,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.search.searcher import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    RandomSearch,
    Searcher,
)
from ray_tpu.tune.search.bohb import TuneBOHB
from ray_tpu.tune.search.tpe import TPESearch
from ray_tpu.tune.trainable import Trainable, with_parameters, wrap_function
from ray_tpu.tune.tuner import TuneConfig, Tuner, run

# ASHAScheduler is the reference's public alias.
ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "DistributeResources",
    "HyperBandForBOHB",
    "HyperBandScheduler",
    "ResourceChangingScheduler",
    "TuneBOHB",
    "BasicVariantGenerator",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "RandomSearch",
    "TPESearch",
    "PB2",
    "ResultGrid",
    "Searcher",
    "Trainable",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "choice",
    "grid_search",
    "loguniform",
    "qrandn",
    "quniform",
    "randint",
    "randn",
    "run",
    "sample_from",
    "uniform",
    "with_parameters",
    "wrap_function",
]
