"""Trial schedulers.

Reference: tune/schedulers/ — ASHA (async_hyperband.py:17,185 _Bracket), PBT
(pbt.py:216 exploit/explore, _explore :49), MedianStopping
(median_stopping_rule.py), FIFO. Decisions returned to the controller:
CONTINUE / STOP / PAUSE.
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Dict, Optional

from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.search.sample import Domain, LogUniform


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    PAUSE = "PAUSE"

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]) -> None:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    def _score(self, result: dict) -> float:
        value = result[self.metric]
        return value if self.mode == "max" else -value

    def on_trial_add(self, trial: Trial) -> None:
        pass

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        return TrialScheduler.CONTINUE

    def on_trial_complete(self, trial: Trial, result: Optional[dict]) -> None:
        pass

    def on_trial_remove(self, trial: Trial) -> None:
        pass

    def may_resume(self, trial: Trial) -> bool:
        """Whether the controller may restart this PAUSED trial now
        (synchronous schedulers hold rung members until the cohort lands)."""
        return True


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py).

    Rung milestones at grace_period * reduction_factor^k; at each rung a trial
    stops unless it is in the top 1/reduction_factor of completed rung entries.
    Asynchronous: decisions use whatever results have arrived, no waiting for
    the full rung population.
    """

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
        brackets: int = 1,
    ):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestone -> list of recorded scores
        self._rungs: Dict[float, list] = defaultdict(list)
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t = math.ceil(t * reduction_factor)
        self._milestones = milestones

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return TrialScheduler.CONTINUE
        t = result[self.time_attr]
        if t >= self.max_t:
            return TrialScheduler.STOP
        score = self._score(result)
        decision = TrialScheduler.CONTINUE
        for milestone in self._milestones:
            if t >= milestone and milestone not in self._passed(trial):
                rung = self._rungs[milestone]
                rung.append(score)
                self._passed(trial).add(milestone)
                cutoff = self._cutoff(rung)
                if cutoff is not None and score < cutoff:
                    decision = TrialScheduler.STOP
        return decision

    def _passed(self, trial: Trial) -> set:
        if not hasattr(trial, "_asha_passed"):
            trial._asha_passed = set()
        return trial._asha_passed

    def _cutoff(self, rung: list) -> Optional[float]:
        if len(rung) < self.rf:
            return None  # not enough evidence yet
        q = 1.0 - 1.0 / self.rf
        s = sorted(rung)
        idx = int(q * (len(s) - 1))
        return s[idx]


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score falls below the median of running
    averages at the same step (reference: tune/schedulers/median_stopping_rule.py)."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: str = "max",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._scores: Dict[str, list] = defaultdict(list)

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        if self.metric not in result:
            return TrialScheduler.CONTINUE
        t = result.get(self.time_attr, 0)
        self._scores[trial.trial_id].append(self._score(result))
        if t < self.grace_period or len(self._scores) < self.min_samples:
            return TrialScheduler.CONTINUE
        means = [sum(v) / len(v) for k, v in self._scores.items() if v]
        means.sort()
        median = means[len(means) // 2]
        own_best = max(self._scores[trial.trial_id])
        if own_best < median:
            return TrialScheduler.STOP
        return TrialScheduler.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py:216).

    Past each perturbation_interval, bottom-quantile trials EXPLOIT (restore
    the checkpoint of a random top-quantile trial) then EXPLORE (mutate
    hyperparameters: resample with prob `resample_probability`, else scale
    continuous values by 1.2/0.8). Requires checkpointable trainables; the
    controller performs the actual save/restore when it sees the decision.
    """

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: str = "max",
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[dict] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._latest: Dict[str, float] = {}
        # trial_id -> (source_trial, new_config) set when exploit is due;
        # the controller pops and applies it.
        self.pending_exploits: Dict[str, tuple] = {}
        self._trials: Dict[str, Trial] = {}

    def on_trial_add(self, trial: Trial) -> None:
        self._trials[trial.trial_id] = trial

    def on_trial_remove(self, trial: Trial) -> None:
        self._trials.pop(trial.trial_id, None)
        self._latest.pop(trial.trial_id, None)

    def on_trial_complete(self, trial: Trial, result: Optional[dict]) -> None:
        self.on_trial_remove(trial)

    def _quantiles(self):
        scored = [
            (tid, self._latest[tid]) for tid in self._trials if tid in self._latest
        ]
        if len(scored) < 2:
            return [], []
        scored.sort(key=lambda kv: kv[1])
        n = max(1, int(len(scored) * self.quantile))
        bottom = [tid for tid, _ in scored[:n]]
        top = [tid for tid, _ in scored[-n:]]
        return bottom, top

    def _explore(self, config: dict) -> dict:
        new = dict(config)
        for key, mutation in self.mutations.items():
            current = new.get(key)
            if isinstance(mutation, Domain):
                if current is None or self._rng.random() < self.resample_prob:
                    new[key] = mutation.sample(self._rng)
                else:
                    new[key] = mutation.perturb(current, self._rng)
            elif isinstance(mutation, list):
                if current in mutation and self._rng.random() >= self.resample_prob:
                    # step to a neighbor value
                    i = mutation.index(current)
                    j = min(len(mutation) - 1, max(0, i + self._rng.choice([-1, 1])))
                    new[key] = mutation[j]
                else:
                    new[key] = self._rng.choice(mutation)
            elif callable(mutation):
                new[key] = mutation()
        return new

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        if self.metric not in result:
            return TrialScheduler.CONTINUE
        t = result.get(self.time_attr, 0)
        self._latest[trial.trial_id] = self._score(result)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return TrialScheduler.CONTINUE
        self._last_perturb[trial.trial_id] = t
        bottom, top = self._quantiles()
        if trial.trial_id in bottom and top:
            src_id = self._rng.choice(top)
            if src_id != trial.trial_id:
                src = self._trials[src_id]
                # Clone the source's config, then explore around it.
                new_config = self._explore(dict(src.config))
                self.pending_exploits[trial.trial_id] = (src, new_config)
        return TrialScheduler.CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: tune/schedulers/hyperband.py).

    Trials are assigned round-robin to brackets of decreasing initial budget;
    within a bracket, successive-halving keeps the top 1/eta of trials each
    round and multiplies their budget by eta. Unlike ASHA, halving waits for
    the whole bracket cohort to reach the milestone (paused trials resume when
    the cohort decision lands), so no trial is judged on partial evidence.
    """

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: str = "max",
        max_t: int = 81,
        reduction_factor: float = 3,
        brackets: int = 1,
    ):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        # Bracket b's cohort starts at budget max_t * eta^-(s_max - b):
        # bracket 0 explores most aggressively (smallest initial budget).
        self._bracket_budgets = [
            max(1, int(round(max_t * reduction_factor ** (-(s_max - b)))))
            for b in range(min(brackets, s_max + 1))
        ]
        self._next_bracket = 0
        # (bracket, milestone) -> {trial id: score at that milestone}; keying
        # by milestone keeps late-added trials out of veterans' rungs.
        self._cohorts: Dict[tuple, dict] = defaultdict(dict)
        self._bracket_of: Dict[str, int] = {}
        self._milestone_of: Dict[str, int] = {}
        self._trials: list = []
        # Cohort losers that were PAUSED when the halving decision landed;
        # they stop on their next report.
        self._doomed: set = set()
        # Rung members paused awaiting their cohort's halving decision.
        self._held: set = set()

    def on_trial_add(self, trial: Trial) -> None:
        bracket = self._next_bracket
        self._next_bracket = (self._next_bracket + 1) % len(self._bracket_budgets)
        self._bracket_of[trial.trial_id] = bracket
        self._milestone_of[trial.trial_id] = self._bracket_budgets[bracket]
        self._trials.append(trial)

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return TrialScheduler.CONTINUE
        if trial.trial_id in self._doomed:
            return TrialScheduler.STOP
        t = result[self.time_attr]
        if t >= self.max_t:
            return TrialScheduler.STOP
        milestone = self._milestone_of.get(trial.trial_id, self.max_t)
        if t < milestone:
            return TrialScheduler.CONTINUE
        bracket = self._bracket_of.get(trial.trial_id, 0)
        self._cohorts[(bracket, milestone)][trial.trial_id] = self._score(result)
        self._maybe_halve(bracket, milestone)
        if trial.trial_id in self._doomed:
            self._doomed.discard(trial.trial_id)
            return TrialScheduler.STOP
        if self._milestone_of.get(trial.trial_id, milestone) > milestone:
            return TrialScheduler.CONTINUE  # halving landed; promoted
        self._held.add(trial.trial_id)
        return TrialScheduler.PAUSE

    def _maybe_halve(self, bracket: int, milestone: int) -> None:
        """Run the rung's halving once every live member has reported.
        Trials added after a halving sit at a smaller milestone and form
        their own cohort (synchronous — the sole difference from ASHA)."""
        cohort = self._cohorts.get((bracket, milestone))
        if not cohort:
            return
        live = [
            tr.trial_id
            for tr in self._trials
            if self._bracket_of.get(tr.trial_id) == bracket
            and self._milestone_of.get(tr.trial_id) == milestone
            and tr.status not in (Trial.TERMINATED, Trial.ERROR)
        ] or list(cohort)
        if not all(tid in cohort for tid in live):
            return
        scores = sorted(cohort.values(), reverse=True)
        keep_n = max(1, int(len(scores) / self.eta))
        cutoff = scores[keep_n - 1]
        next_milestone = min(self.max_t, int(milestone * self.eta))
        for tid, score in cohort.items():
            self._milestone_of[tid] = next_milestone
            self._held.discard(tid)
            if score < cutoff:
                self._doomed.add(tid)
        del self._cohorts[(bracket, milestone)]

    def on_trial_complete(self, trial: Trial, result: Optional[dict]) -> None:
        # A member erroring/finishing must not deadlock its rung: drop it and
        # re-check whether the cohorts it gated can now halve. Terminal trials
        # also leave the tracking maps so long experiments don't grow them
        # (and _maybe_halve's live scan stays proportional to live trials).
        self._held.discard(trial.trial_id)
        self._doomed.discard(trial.trial_id)
        bracket = self._bracket_of.get(trial.trial_id)
        if bracket is None:
            return
        self._trials = [t for t in self._trials if t.trial_id != trial.trial_id]
        self._bracket_of.pop(trial.trial_id, None)
        self._milestone_of.pop(trial.trial_id, None)
        for (b, milestone) in list(self._cohorts):
            if b == bracket:
                self._cohorts[(b, milestone)].pop(trial.trial_id, None)
                self._maybe_halve(b, milestone)

    def may_resume(self, trial: Trial) -> bool:
        # Doomed trials resume (to receive their STOP); held rung members
        # wait for the cohort.
        return trial.trial_id not in self._held

    def on_trial_remove(self, trial: Trial) -> None:
        # The controller also routes PAUSE through removal — a paused trial's
        # milestone score must stay in the cohort or halving never fires.
        # Terminal removals go through on_trial_complete.
        if trial.status in (Trial.TERMINATED, Trial.ERROR):
            self.on_trial_complete(trial, None)


class HyperBandForBOHB(HyperBandScheduler):
    """HyperBand variant for BOHB (reference: tune/schedulers/hb_bohb.py).

    Two changes against plain HyperBand, both serving the paired TuneBOHB
    searcher's per-budget models:

      * brackets fill SEQUENTIALLY, not round-robin — each bracket's cohort
        then shares an initial budget, so rung observations are
        budget-comparable when they reach the searcher;
      * the controller's searcher coupling does the rest: every result is
        routed to TuneBOHB.on_trial_result, which buckets scores by the
        rung milestones this scheduler runs (same max_t/reduction_factor).

    Construct both halves with the same max_t and reduction_factor.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._bracket_fill = 0  # trials assigned to the current bracket
        self._bracket_capacity = [
            # Successive-halving cohort size: bracket b starts eta^(rungs)
            # trials where rungs = number of halvings to reach max_t.
            max(
                1,
                int(
                    round(
                        self.eta
                        ** max(
                            0,
                            round(
                                math.log(self.max_t / budget)
                                / math.log(self.eta)
                            ),
                        )
                    )
                ),
            )
            for budget in self._bracket_budgets
        ]

    def on_trial_add(self, trial: Trial) -> None:
        bracket = self._next_bracket
        self._bracket_fill += 1
        if self._bracket_fill >= self._bracket_capacity[bracket]:
            self._bracket_fill = 0
            self._next_bracket = (
                self._next_bracket + 1
            ) % len(self._bracket_budgets)
        self._bracket_of[trial.trial_id] = bracket
        self._milestone_of[trial.trial_id] = self._bracket_budgets[bracket]
        self._trials.append(trial)


class ResourceChangingScheduler(TrialScheduler):
    """Re-pack running trials onto freed capacity
    (reference: tune/schedulers/resource_changing_scheduler.py).

    Wraps a base scheduler; after each result the
    `resources_allocation_function(controller, trial, result, scheduler)`
    may return a new resource request for the trial. A changed request
    PAUSES the trial (checkpointing it); the controller applies the pending
    request when the trial resumes, so the fresh actor is created at the
    new size. On TPUs this is the utilization story: a finished trial frees
    a slice and survivors grow into it.
    """

    def __init__(
        self,
        base_scheduler: Optional[TrialScheduler] = None,
        resources_allocation_function=None,
    ):
        self.base = base_scheduler or FIFOScheduler()
        super().__init__(self.base.metric, self.base.mode)
        self.alloc_fn = resources_allocation_function
        # trial_id -> resources dict, applied by the controller at resume.
        self.pending_resources: Dict[str, dict] = {}
        self._controller = None  # injected by the controller at run start

    def set_controller(self, controller) -> None:
        self._controller = controller

    def set_search_properties(self, metric, mode) -> None:
        super().set_search_properties(metric, mode)
        self.base.set_search_properties(metric, mode)

    def on_trial_add(self, trial: Trial) -> None:
        self.base.on_trial_add(trial)

    def on_trial_remove(self, trial: Trial) -> None:
        self.base.on_trial_remove(trial)

    def on_trial_complete(self, trial: Trial, result: Optional[dict]) -> None:
        self.base.on_trial_complete(trial, result)

    def may_resume(self, trial: Trial) -> bool:
        return self.base.may_resume(trial)

    @property
    def pending_exploits(self):
        # PBT bases surface their exploits through the wrapper.
        return getattr(self.base, "pending_exploits", None)

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        decision = self.base.on_trial_result(trial, result)
        if decision != TrialScheduler.CONTINUE or self.alloc_fn is None:
            return decision
        try:
            new = self.alloc_fn(self._controller, trial, result, self)
        except Exception:
            return decision
        if new and dict(new) != dict(trial.resources):
            self.pending_resources[trial.trial_id] = dict(new)
            return TrialScheduler.PAUSE
        return decision


class DistributeResources:
    """Default allocation policy: grow each live trial's CPU/TPU request to
    an even share of the cluster total (the reference's
    DistributeResources). Shrinks never below the base request."""

    def __init__(self, base_resources: Optional[dict] = None):
        self.base = dict(base_resources or {"CPU": 1.0})

    def __call__(self, controller, trial, result, scheduler):
        import ray_tpu

        total = ray_tpu.cluster_resources()
        live = max(1, len(getattr(controller, "_live", {}) or {1: 1}))
        new = dict(trial.resources)
        for key in ("CPU", "TPU"):
            if key not in total:
                continue
            base = self.base.get(key, 0.0)
            if not base and not new.get(key):
                continue
            share = math.floor(total[key] / live)
            new[key] = max(base, float(share))
        return new


class PB2(PopulationBasedTraining):
    """PBT with a GP-bandit explore step (reference: tune/schedulers/pb2.py,
    Parker-Holder et al. 2020). Instead of random 1.2x/0.8x perturbation,
    the continuous hyperparameters of the exploited config are chosen by
    UCB over a Gaussian-process fit to (hyperparams -> observed score
    improvement) across the population's recent perturbation windows.
    Implemented natively (no GPy/sklearn in the sealed image): an RBF-kernel
    GP on normalized inputs with a small jitter, UCB argmax over sampled
    candidates. Non-continuous mutations fall back to PBT's explore."""

    def __init__(self, *args, ucb_kappa: float = 2.0, n_candidates: int = 64,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.ucb_kappa = ucb_kappa
        self.n_candidates = n_candidates
        # (hyperparam vector, score delta) observations per continuous key set
        self._gp_data: list = []
        self._prev_score: Dict[str, float] = {}

    # -- data collection ----------------------------------------------------

    def _continuous_keys(self) -> list:
        # Only genuinely continuous domains ride the GP: Randint/QUniform
        # values must stay integral/quantized, so they keep PBT's explore.
        from ray_tpu.tune.search.sample import Uniform

        return sorted(
            key for key, m in self.mutations.items()
            if isinstance(m, (Uniform, LogUniform))
        )

    def _bounds(self, key):
        m = self.mutations[key]
        import math as _math

        if isinstance(m, LogUniform):
            return _math.log(m.lower), _math.log(m.upper), True
        return float(m.lower), float(m.upper), False

    def _vec(self, config: dict) -> list:
        import math as _math

        out = []
        for key in self._continuous_keys():
            lo, hi, logspace = self._bounds(key)
            v = float(config.get(key, (lo + hi) / 2.0))
            if logspace:
                v = _math.log(max(v, 1e-300))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return out

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        if self.metric in result:
            tid = trial.trial_id
            score = self._score(result)
            prev = self._prev_score.get(tid)
            if prev is not None:
                self._gp_data.append((self._vec(trial.config), score - prev))
                # Recent-window cap keeps the GP solve cheap (n^3) and the
                # model focused on the current training phase (the PB2
                # paper's time-varying treatment, simplified to a window).
                if len(self._gp_data) > 64:
                    self._gp_data = self._gp_data[-64:]
            self._prev_score[tid] = score
        return super().on_trial_result(trial, result)

    # -- GP-UCB explore ------------------------------------------------------

    def _explore(self, config: dict) -> dict:
        keys = self._continuous_keys()
        if len(self._gp_data) < 4 or not keys:
            return super()._explore(config)
        new = super()._explore(config)  # categorical/fallback mutations
        best = self._ucb_argmax()
        if best is None:
            return new
        import math as _math

        for key, unit in zip(keys, best):
            lo, hi, logspace = self._bounds(key)
            v = lo + unit * (hi - lo)
            new[key] = _math.exp(v) if logspace else v
        return new

    def _ucb_argmax(self):
        import numpy as np

        data = self._gp_data
        n = len(data)
        xs = np.asarray([x for x, _ in data], dtype=np.float64)  # [n, d]
        ys = np.asarray([y for _, y in data], dtype=np.float64)
        sd = ys.std() or 1.0
        ys_n = (ys - ys.mean()) / sd
        ls = 0.3  # RBF lengthscale in normalized space
        noise = 1e-2
        d2 = ((xs[:, None, :] - xs[None, :, :]) ** 2).sum(-1)
        K = np.exp(-0.5 * d2 / (ls * ls)) + noise * np.eye(n)
        try:
            chol = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return None
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, ys_n))

        dims = xs.shape[1]
        cands = np.asarray(
            [[self._rng.random() for _ in range(dims)]
             for _ in range(self.n_candidates)]
        )  # [m, d]
        kv = np.exp(
            -0.5 * ((cands[:, None, :] - xs[None, :, :]) ** 2).sum(-1)
            / (ls * ls)
        )  # [m, n]
        mu = kv @ alpha
        v = np.linalg.solve(chol, kv.T)  # [n, m]
        var = np.maximum(1.0 - (v * v).sum(0), 1e-9)
        ucb = mu + self.ucb_kappa * np.sqrt(var)
        return cands[int(ucb.argmax())].tolist()
