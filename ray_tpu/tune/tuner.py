"""Tuner: the modern Tune entry point.

Reference: tune/tuner.py:320 Tuner.fit → impl/tuner_internal.py:583 →
tune/tune.py:293 run. `Tuner(trainable, param_space=..., tune_config=...,
run_config=...)` — trainable may be a function(config), a Trainable subclass,
or a ray_tpu Trainer instance (wrapped into a 1-trial run the way
base_trainer.py:559 does).
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ray_tpu.air.config import RunConfig
from ray_tpu.tune.execution.tune_controller import TuneController
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.trainable import Trainable, wrap_function


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    reuse_actors: bool = False
    seed: Optional[int] = None


def _as_trainable_cls(trainable: Any) -> type:
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable) and not isinstance(trainable, type):
        # Trainer instances (duck-typed: has .fit and ._as_trainable).
        if hasattr(trainable, "as_trainable"):
            return trainable.as_trainable()
        return wrap_function(trainable)
    if hasattr(trainable, "as_trainable"):
        return trainable.as_trainable()
    raise TypeError(f"Cannot convert {trainable!r} to a Trainable")


class Tuner:
    def __init__(
        self,
        trainable: Any,
        *,
        param_space: Optional[dict] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        resources_per_trial: Optional[dict] = None,
        _controller_kwargs: Optional[dict] = None,
    ):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._resources = resources_per_trial
        self._controller_kwargs = _controller_kwargs or {}
        self._controller: Optional[TuneController] = None
        self._seed_trials: list = []

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        rc = self._run_config
        stop = dict(rc.stop) if getattr(rc, "stop", None) else {}
        exp_dir = ""
        if getattr(rc, "storage_path", None) or getattr(rc, "name", None):
            # resolved_storage_path() already includes the run name.
            exp_dir = rc.resolved_storage_path()
        failure_cfg = getattr(rc, "failure_config", None)
        max_failures = getattr(failure_cfg, "max_failures", 0) if failure_cfg else 0
        ckpt_cfg = getattr(rc, "checkpoint_config", None)
        checkpoint_at_end = (
            getattr(ckpt_cfg, "checkpoint_at_end", True) if ckpt_cfg else True
        )

        checkpoint_frequency = (
            getattr(ckpt_cfg, "checkpoint_frequency", 0) if ckpt_cfg else 0
        )

        self._controller = TuneController(
            _as_trainable_cls(self._trainable),
            param_space=self._param_space,
            searcher=tc.search_alg,
            scheduler=tc.scheduler,
            metric=tc.metric,
            mode=tc.mode,
            num_samples=tc.num_samples,
            stop=stop,
            max_concurrent_trials=tc.max_concurrent_trials,
            resources_per_trial=self._resources,
            max_failures=max_failures,
            checkpoint_at_end=checkpoint_at_end,
            checkpoint_frequency=checkpoint_frequency,
            experiment_dir=exp_dir,
            seed=tc.seed,
            reuse_actors=tc.reuse_actors,
            seed_trials=self._seed_trials,
            **self._controller_kwargs,
        )
        self._save_tuner_state(self._controller._experiment_dir)
        trials = self._controller.run()
        return ResultGrid(trials, tc.metric, tc.mode)

    def _save_tuner_state(self, exp_dir: str) -> None:
        try:
            with open(os.path.join(exp_dir, "tuner.pkl"), "wb") as f:
                pickle.dump(
                    {
                        "param_space": self._param_space,
                        "tune_config": self._tune_config,
                        "run_config": self._run_config,
                        "resources_per_trial": self._resources,
                    },
                    f,
                )
        except Exception:
            pass  # non-picklable search spaces: resume unavailable, fit fine

    @classmethod
    def restore(cls, path: str, trainable: Any) -> "Tuner":
        """Rebuild a Tuner from a saved experiment dir. Unfinished (non-
        TERMINATED) trials are re-seeded and re-run on fit(), resuming from
        their last persisted checkpoint when one exists."""
        import json

        with open(os.path.join(path, "tuner.pkl"), "rb") as f:
            state = pickle.load(f)
        tuner = cls(trainable, **state)
        state_file = os.path.join(path, "experiment_state.json")
        seeds = []
        if os.path.exists(state_file):
            with open(state_file) as f:
                exp = json.load(f)
            for meta in exp.get("trials", []):
                if meta.get("status") == "TERMINATED":
                    continue
                ckpt = None
                ckpt_file = os.path.join(
                    path, f"trial_{meta['trial_id']}", "checkpoint.pkl"
                )
                if os.path.exists(ckpt_file):
                    with open(ckpt_file, "rb") as f:
                        ckpt = pickle.load(f)
                config = meta.get("config")
                if isinstance(config, dict):
                    seeds.append((config, ckpt))
        tuner._seed_trials = seeds
        # Seeded trials replace fresh sampling: don't re-expand the space.
        if seeds:
            tuner._tune_config.num_samples = 0
            tuner._param_space = {}
        return tuner


def run(
    trainable: Any,
    *,
    config: Optional[dict] = None,
    metric: Optional[str] = None,
    mode: str = "max",
    num_samples: int = 1,
    stop: Optional[dict] = None,
    search_alg: Optional[Searcher] = None,
    scheduler: Optional[TrialScheduler] = None,
    resources_per_trial: Optional[dict] = None,
    max_concurrent_trials: Optional[int] = None,
    **kwargs,
) -> ResultGrid:
    """Legacy tune.run surface (reference: tune/tune.py:293)."""
    controller = TuneController(
        _as_trainable_cls(trainable),
        param_space=config or {},
        searcher=search_alg,
        scheduler=scheduler,
        metric=metric,
        mode=mode,
        num_samples=num_samples,
        stop=stop,
        resources_per_trial=resources_per_trial,
        max_concurrent_trials=max_concurrent_trials,
        **kwargs,
    )
    trials = controller.run()
    return ResultGrid(trials, metric, mode)
