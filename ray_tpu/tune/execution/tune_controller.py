"""TuneController: the experiment event loop.

Reference: tune/execution/tune_controller.py (:49 TuneController, step :267) —
an event loop that (1) asks the searcher for new configs and starts trial
actors while resources allow, (2) consumes trial results as they arrive,
(3) routes them through the scheduler (CONTINUE/STOP/PAUSE), (4) applies PBT
exploit/explore via save/restore on the trial actors, (5) snapshots experiment
state for resume (tune/execution/experiment_state.py).
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.searcher import BasicVariantGenerator, Searcher
from ray_tpu.tune.trainable import DONE, Trainable


class _TrialRunner:
    """Actor hosting one Trainable instance (reference: the trial actor —
    Trainable IS the actor class upstream; we wrap so user classes need no
    actor decoration)."""

    def __init__(self, trainable_cls: type, config: dict, trial_id: str):
        # Set trial_id on the instance BEFORE __init__ (setup() reads it);
        # a class attribute would race across concurrently-built trials in
        # the in-process runtime.
        trainable = trainable_cls.__new__(trainable_cls)
        trainable.trial_id = trial_id
        trainable.__init__(config)
        self._trainable: Trainable = trainable

    def train(self) -> dict:
        return self._trainable.train()

    def save(self) -> dict:
        return self._trainable.save()

    def restore(self, state: dict) -> None:
        self._trainable.restore(state)

    def reset(self, config: dict) -> bool:
        return self._trainable.reset(config)

    def stop(self) -> None:
        self._trainable.stop()


class TuneController:
    def __init__(
        self,
        trainable_cls: type,
        *,
        param_space: Optional[dict] = None,
        searcher: Optional[Searcher] = None,
        scheduler: Optional[TrialScheduler] = None,
        metric: Optional[str] = None,
        mode: str = "max",
        num_samples: int = 1,
        stop: Optional[dict] = None,
        max_concurrent_trials: Optional[int] = None,
        resources_per_trial: Optional[dict] = None,
        max_failures: int = 0,
        checkpoint_at_end: bool = False,
        experiment_dir: str = "",
        seed: Optional[int] = None,
        reuse_actors: bool = False,
        callbacks: Optional[list] = None,
        checkpoint_frequency: int = 0,
        seed_trials: Optional[list] = None,
    ):
        self._trainable_cls = trainable_cls
        # With a user searcher, num_samples caps the number of suggestions
        # (reference: tune.run num_samples semantics); the default
        # grid/random generator bakes num_samples into the variant stream.
        self._suggest_cap = num_samples if searcher is not None else None
        self._searcher = searcher or BasicVariantGenerator(
            param_space or {}, num_samples=num_samples, seed=seed
        )
        self._searcher.set_search_properties(metric, mode, param_space or {})
        self._scheduler = scheduler or FIFOScheduler(metric, mode)
        self._scheduler.set_search_properties(metric, mode)
        if hasattr(self._scheduler, "set_controller"):
            # ResourceChangingScheduler's allocation function inspects the
            # controller (live trials, cluster headroom).
            self._scheduler.set_controller(self)
        self.metric = metric
        self.mode = mode
        self._stop_criteria = stop or {}
        self._max_concurrent = max_concurrent_trials or 0
        self._resources = resources_per_trial or {"CPU": 1.0}
        self._max_failures = max_failures
        self._checkpoint_at_end = checkpoint_at_end
        self._experiment_dir = experiment_dir or os.path.join(
            os.path.expanduser("~/ray_tpu_results"), f"exp_{int(time.time())}"
        )
        os.makedirs(self._experiment_dir, exist_ok=True)
        self._reuse_actors = reuse_actors
        self._callbacks = callbacks or []
        self._checkpoint_frequency = checkpoint_frequency

        self.trials: List[Trial] = []
        self._live: Dict[str, Trial] = {}  # trial_id -> trial with future
        self._idle_actors: list = []  # for reuse_actors
        self._exhausted = False
        # Restored experiments seed unfinished trials: (config, ckpt_dict|None).
        for config, ckpt in seed_trials or []:
            trial = Trial(
                trainable_cls.__name__,
                config,
                trial_id=f"t{len(self.trials):05d}",
                experiment_dir=self._experiment_dir,
                resources=dict(self._resources),
                max_failures=max_failures,
            )
            if ckpt is not None:
                trial.checkpoint = Checkpoint.from_dict(ckpt)
            self.trials.append(trial)
            self._scheduler.on_trial_add(trial)

    # -- lifecycle -------------------------------------------------------

    def _next_trial(self) -> Optional[Trial]:
        if self._exhausted:
            return None
        if self._suggest_cap is not None and len(self.trials) >= self._suggest_cap:
            self._exhausted = True
            return None
        if hasattr(self._searcher, "is_saturated") and self._searcher.is_saturated():
            return None
        trial_id = f"t{len(self.trials):05d}"
        config = self._searcher.suggest(trial_id)
        if config is None:
            # None while not saturated means the space is exhausted.
            saturated = getattr(self._searcher, "is_saturated", lambda: False)()
            self._exhausted = not saturated
            return None
        trial = Trial(
            self._trainable_cls.__name__,
            config,
            trial_id=trial_id,
            experiment_dir=self._experiment_dir,
            resources=dict(self._resources),
            max_failures=self._max_failures,
        )
        self.trials.append(trial)
        self._scheduler.on_trial_add(trial)
        for cb in self._callbacks:
            cb.on_trial_start(trial) if hasattr(cb, "on_trial_start") else None
        return trial

    def _has_resources(self, trial: Trial) -> bool:
        avail = ray_tpu.available_resources()
        return all(avail.get(k, 0.0) >= v for k, v in trial.resources.items())

    def _actor_options(self, trial: Trial) -> dict:
        return {
            "num_cpus": trial.resources.get("CPU", 0.0),
            "num_tpus": trial.resources.get("TPU", 0.0),
            "resources": {
                k: v for k, v in trial.resources.items() if k not in ("CPU", "TPU")
            },
        }

    def _create_actor(self, trial: Trial):
        actor_cls = ray_tpu.remote(_TrialRunner).options(**self._actor_options(trial))
        return actor_cls.remote(self._trainable_cls, trial.config, trial.trial_id)

    def _start_trial(self, trial: Trial) -> None:
        # A resized trial (ResourceChangingScheduler, applied in step()'s
        # admission path) must get a FRESH actor at the new size.
        resized = getattr(trial, "_no_actor_reuse", False)
        trial._no_actor_reuse = False
        if not resized and self._reuse_actors and self._idle_actors:
            actor = self._idle_actors.pop()
            ok = ray_tpu.get(actor.reset.remote(trial.config))
            if ok:
                trial.actor = actor
                trial.set_status(Trial.RUNNING)
                trial.future = actor.train.remote()
                self._live[trial.trial_id] = trial
                return
            ray_tpu.kill(actor)
        trial.actor = self._create_actor(trial)
        # PAUSED and restored-from-disk trials resume from their checkpoint.
        if trial.checkpoint is not None:
            ray_tpu.get(trial.actor.restore.remote(trial.checkpoint.to_dict()))
        trial.set_status(Trial.RUNNING)
        trial.future = trial.actor.train.remote()
        self._live[trial.trial_id] = trial

    def _stop_trial(self, trial: Trial, status: str, save_final: bool = False) -> None:
        if trial.actor is not None:
            try:
                if save_final and self._checkpoint_at_end:
                    trial.checkpoint = Checkpoint.from_dict(
                        ray_tpu.get(trial.actor.save.remote())
                    )
                ray_tpu.get(trial.actor.stop.remote())
            except Exception:
                pass
            if self._reuse_actors and status == Trial.TERMINATED:
                self._idle_actors.append(trial.actor)
            else:
                try:
                    ray_tpu.kill(trial.actor)
                except Exception:
                    pass
            trial.actor = None
        trial.future = None
        trial.set_status(status)
        self._live.pop(trial.trial_id, None)
        self._scheduler.on_trial_remove(trial)

    # -- stop criteria ---------------------------------------------------

    def _should_stop(self, result: dict) -> bool:
        if result.get(DONE):
            return True
        # Reference semantics (tune/stopper.py dict stopper): stop when
        # result[key] >= threshold, independent of the optimization mode.
        for key, threshold in self._stop_criteria.items():
            if key in result and result[key] >= threshold:
                return True
        return False

    # -- PBT exploit -----------------------------------------------------

    def _apply_exploits(self) -> None:
        pending = getattr(self._scheduler, "pending_exploits", None)
        if not pending:
            return
        for target_id, (src, new_config) in list(pending.items()):
            pending.pop(target_id)
            target = next(
                (t for t in self.trials if t.trial_id == target_id), None
            )
            if target is None or src.actor is None or target.actor is None:
                continue
            # Rendezvous: both actors are between train() calls for the target;
            # src may be mid-train — save() queues behind it (ordered actor queue).
            state = ray_tpu.get(src.actor.save.remote())
            target.config = new_config
            reset_ok = ray_tpu.get(target.actor.reset.remote(new_config))
            if not reset_ok:
                # Restart the actor with the new config, then restore weights.
                # The pending train() future on the old actor dies with it —
                # resubmit on the new actor so the controller never consumes a
                # stale ref (that would read as a spurious trial failure).
                ray_tpu.kill(target.actor)
                target.actor = self._create_actor(target)
                ray_tpu.get(target.actor.restore.remote(state))
                if target.trial_id in self._live:
                    target.future = target.actor.train.remote()
            else:
                ray_tpu.get(target.actor.restore.remote(state))

    # -- main loop -------------------------------------------------------

    def step(self, timeout: float = 10.0) -> bool:
        """One controller tick. Returns False when the experiment is over."""
        # 1. Launch new trials while capacity allows.
        while True:
            if self._max_concurrent and len(self._live) >= self._max_concurrent:
                break
            candidate = next(
                (
                    t
                    for t in self.trials
                    if t.status == Trial.PENDING
                    or (
                        t.status == Trial.PAUSED
                        # Synchronous schedulers (HyperBand) hold paused
                        # trials at a rung until the cohort decision lands.
                        and self._scheduler.may_resume(t)
                    )
                ),
                None,
            )
            if candidate is None:
                candidate = self._next_trial()
            if candidate is None:
                break
            # Apply a pending ResourceChangingScheduler resize BEFORE the
            # admission check: admitting against the stale size could start
            # an actor the cluster can't place and block the event loop on
            # its restore.
            pending_resources = getattr(
                self._scheduler, "pending_resources", None
            )
            if pending_resources and candidate.trial_id in pending_resources:
                candidate.resources = dict(
                    pending_resources.pop(candidate.trial_id)
                )
                candidate._no_actor_reuse = True
            if not self._has_resources(candidate) and self._live:
                break  # wait for a slot; if nothing live, start anyway (queue)
            self._start_trial(candidate)

        if not self._live:
            return False

        # 2. Wait for any trial result, then harvest everything already ready —
        # processing only the first ready future would starve later trials
        # (their 1-deep report queues park the runner threads).
        futures = {t.future: t for t in self._live.values() if t.future is not None}
        ready, rest = ray_tpu.wait(
            list(futures.keys()), num_returns=1, timeout=timeout
        )
        if ready and rest:
            more, _ = ray_tpu.wait(rest, num_returns=len(rest), timeout=0)
            ready = ready + more
        for ref in ready:
            trial = futures[ref]
            try:
                result = ray_tpu.get(ref)
            except Exception as e:
                trial.num_failures += 1
                trial.error_msg = repr(e)
                if trial.should_recover():
                    self._restart_trial(trial)
                else:
                    self._stop_trial(trial, Trial.ERROR)
                    self._searcher.on_trial_complete(trial.trial_id, error=True)
                    self._scheduler.on_trial_complete(trial, None)
                continue

            trial.error_msg = None  # recovered if previously failed
            trial.last_result = result
            trial.results.append(result)
            trial.iteration = result.get("training_iteration", trial.iteration + 1)
            self._searcher.on_trial_result(trial.trial_id, result)
            for cb in self._callbacks:
                if hasattr(cb, "on_trial_result"):
                    cb.on_trial_result(trial, result)

            if self._should_stop(result):
                self._stop_trial(trial, Trial.TERMINATED, save_final=True)
                self._searcher.on_trial_complete(trial.trial_id, result)
                self._scheduler.on_trial_complete(trial, result)
                continue

            # Periodic checkpointing (CheckpointConfig.checkpoint_frequency).
            if (
                self._checkpoint_frequency
                and trial.iteration % self._checkpoint_frequency == 0
            ):
                trial.checkpoint = Checkpoint.from_dict(
                    ray_tpu.get(trial.actor.save.remote())
                )

            decision = self._scheduler.on_trial_result(trial, result)
            if decision == TrialScheduler.STOP:
                self._stop_trial(trial, Trial.TERMINATED, save_final=True)
                self._searcher.on_trial_complete(trial.trial_id, result)
                self._scheduler.on_trial_complete(trial, result)
            elif decision == TrialScheduler.PAUSE:
                state = ray_tpu.get(trial.actor.save.remote())
                trial.checkpoint = Checkpoint.from_dict(state)
                self._stop_trial(trial, Trial.PAUSED)
            else:
                trial.future = trial.actor.train.remote()

        # 3. PBT exploits after the batch of results.
        self._apply_exploits()

        # 4. Periodic experiment-state snapshot.
        self._save_experiment_state()
        return True

    def _restart_trial(self, trial: Trial) -> None:
        try:
            if trial.actor is not None:
                ray_tpu.kill(trial.actor)
        except Exception:
            pass
        state = trial.checkpoint.to_dict() if trial.checkpoint else None
        trial.actor = self._create_actor(trial)
        if state is not None:
            ray_tpu.get(trial.actor.restore.remote(state))
        trial.future = trial.actor.train.remote()
        trial.set_status(Trial.RUNNING)
        self._live[trial.trial_id] = trial

    def run(self) -> List[Trial]:
        while self.step():
            pass
        self._save_experiment_state()
        return self.trials

    def train_run_reports(self, rounds_limit: int = 8) -> Dict[str, list]:
        """Per-trial training telemetry. Trainer-backed trials
        (DataParallelTrainer.as_trainable) register their fit's round
        records under the trial id, so trial rounds reuse the SAME records
        the train profiler produced — one telemetry plane for standalone
        fits and tuned ones. Trials may fit more than once (failure
        retries, PBT exploits), hence a list per trial."""
        from ray_tpu.train.observability import list_runs

        trial_ids = {t.trial_id for t in self.trials}
        out: Dict[str, list] = {}
        for run in list_runs(limit=len(trial_ids) * 4 + 8, rounds_limit=rounds_limit):
            if run["name"] in trial_ids:
                out.setdefault(run["name"], []).append(run)
        return out

    # -- experiment state ------------------------------------------------

    def _save_experiment_state(self) -> None:
        path = os.path.join(self._experiment_dir, "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"trials": [t.metadata() for t in self.trials]}, f)
        os.replace(tmp, path)
        # Checkpoints for resumable trials (pickle: configs may be non-JSON).
        for t in self.trials:
            if t.checkpoint is not None:
                with open(os.path.join(t.local_dir, "checkpoint.pkl"), "wb") as f:
                    pickle.dump(t.checkpoint.to_dict(), f)
