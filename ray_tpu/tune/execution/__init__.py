"""Trial execution."""
from ray_tpu.tune.execution.tune_controller import TuneController  # noqa
