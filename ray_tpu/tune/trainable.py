"""Trainable protocol: class API + function-API wrapper.

Reference: tune/trainable/trainable.py (class API; train :350) and
tune/trainable/function_trainable.py (:287,:576 wrap_function) — the function
API runs the user function on a runner thread and turns each `session.report`
into one `step()` result via the air session's 1-deep rendezvous queue
(train/_internal/session.py semantics, see ray_tpu/air/session.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.session import TrainContext, _Session, _set_session

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Class API. Subclasses implement setup/step/save_checkpoint/load_checkpoint."""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}
        self._iteration = 0
        # Monotonic: time_total_s is a duration fed to schedulers/stoppers.
        self._start = time.monotonic()
        self.setup(self.config)

    # -- overridable -----------------------------------------------------
    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self) -> Optional[dict]:
        return None

    def load_checkpoint(self, state: Optional[dict]) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Return True if the trainable can hot-swap configs (actor reuse)."""
        return False

    # -- controller-facing protocol (actor methods) ----------------------
    def train(self) -> dict:
        result = self.step()
        if not isinstance(result, dict):
            raise ValueError(f"step() must return a dict, got {type(result)}")
        self._iteration += 1
        result.setdefault(DONE, False)
        result[TRAINING_ITERATION] = self._iteration
        result.setdefault("time_total_s", time.monotonic() - self._start)
        result.setdefault("trial_id", getattr(self, "trial_id", None))
        return result

    def save(self) -> dict:
        return {
            "trainable_state": {"iteration": self._iteration},
            "user_state": self.save_checkpoint(),
        }

    def restore(self, state: dict) -> None:
        self._iteration = state["trainable_state"]["iteration"]
        self.load_checkpoint(state["user_state"])

    def reset(self, new_config: dict) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = new_config
            self._iteration = 0
        return ok

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps `def train_fn(config)` into the Trainable protocol."""

    _train_fn: Callable = None  # set by wrap_function subclass

    def setup(self, config: dict) -> None:
        self._session = _Session(
            TrainContext(trial_id=getattr(self, "trial_id", "")),
            checkpoint=getattr(self, "_restore_checkpoint", None),
        )
        self._error: list = []
        self._thread: Optional[threading.Thread] = None
        self._last_checkpoint: Optional[Checkpoint] = None

    def _runner(self) -> None:
        _set_session(self._session)
        try:
            self._train_fn(self.config)
        except StopIteration:
            pass
        except BaseException as e:  # surfaced on the next step()
            self._error.append(e)
        finally:
            self._session.finish()
            _set_session(None)

    def step(self) -> dict:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()
        item = self._session.result_queue.get()
        if item is _Session.FINISHED:
            if self._error:
                raise self._error[0]
            # Final sentinel repeats the last reported metrics (reference:
            # function_trainable's last result carries done=True).
            return {**getattr(self, "_last_metrics", {}), DONE: True}
        if item["checkpoint"] is not None:
            self._last_checkpoint = item["checkpoint"]
        metrics = item["metrics"]
        metrics.setdefault(DONE, False)
        self._last_metrics = dict(metrics)
        return metrics

    def save_checkpoint(self) -> Optional[dict]:
        ckpt = self._last_checkpoint
        return None if ckpt is None else ckpt.to_dict()

    def load_checkpoint(self, state: Optional[dict]) -> None:
        if state is not None:
            self._restore_checkpoint = Checkpoint.from_dict(state)
            # Session is rebuilt on next setup; for in-place restore, expose it.
            if hasattr(self, "_session"):
                self._session.loaded_checkpoint = self._restore_checkpoint

    def cleanup(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._session.stop_event.set()
            # Unblock a report() stuck at the rendezvous.
            try:
                self._session.result_queue.get_nowait()
            except Exception:
                pass
            self._thread.join(timeout=2.0)


def wrap_function(train_fn: Callable) -> type:
    """Build a FunctionTrainable subclass around `train_fn(config)`."""

    class _Wrapped(FunctionTrainable):
        _train_fn = staticmethod(train_fn)

    _Wrapped.__name__ = getattr(train_fn, "__name__", "function_trainable")
    return _Wrapped


def with_parameters(fn_or_cls: Any, **kwargs) -> Any:
    """Bind large objects by closure (reference: tune/utils/trainable.py
    with_parameters; the reference ray.put's them — in-process runtime makes
    plain closure capture equivalent)."""
    if isinstance(fn_or_cls, type):
        class _Bound(fn_or_cls):  # type: ignore[misc]
            def setup(self, config):
                super().setup(config, **kwargs)

        _Bound.__name__ = fn_or_cls.__name__
        return _Bound

    def bound(config):
        return fn_or_cls(config, **kwargs)

    bound.__name__ = getattr(fn_or_cls, "__name__", "bound_trainable")
    return bound
