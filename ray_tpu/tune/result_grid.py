"""ResultGrid: the Tuner.fit() return value (reference: tune/result_grid.py)."""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.air.result import Result
from ray_tpu.tune.experiment.trial import Trial


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode
        self._results = [
            Result(
                metrics=t.last_result,
                checkpoint=t.checkpoint,
                error=t.error_msg,
                path=t.local_dir,
                metrics_history=t.results,
                config=dict(t.config or {}),
            )
            for t in trials
        ]

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> list:
        return [r.error for r in self._results if r.error]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("No metric given to get_best_result")
        scored = [r for r in self._results if metric in (r.metrics or {})]
        if not scored:
            raise RuntimeError("No trial reported the metric " + repr(metric))
        key = lambda r: r.metrics[metric]
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        """Per-trial final metrics as a pandas DataFrame."""
        import pandas as pd

        rows = []
        for t in self._trials:
            row = {"trial_id": t.trial_id, "status": t.status}
            row.update({k: v for k, v in (t.last_result or {}).items()})
            row.update({f"config/{k}": v for k, v in t.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)
