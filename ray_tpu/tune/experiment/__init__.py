"""Experiment metadata."""
from ray_tpu.tune.experiment.trial import Trial  # noqa
