"""Trial: one hyperparameter configuration's lifecycle.

Reference: tune/experiment/trial.py — a Trial is pure metadata + state machine;
the controller owns the actor. States follow the reference's:
PENDING → RUNNING → {PAUSED, TERMINATED, ERROR}.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Optional


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"

    def __init__(
        self,
        trainable_name: str,
        config: dict,
        *,
        trial_id: Optional[str] = None,
        experiment_dir: str = "",
        resources: Optional[dict] = None,
        max_failures: int = 0,
    ):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.trainable_name = trainable_name
        self.config = config
        self.status = Trial.PENDING
        self.resources = resources or {"CPU": 1.0}
        self.max_failures = max_failures
        self.num_failures = 0
        self.experiment_dir = experiment_dir
        self.last_result: dict = {}
        self.results: list[dict] = []
        self.checkpoint = None  # in-memory Checkpoint (latest)
        self.error_msg: Optional[str] = None
        self.start_time: Optional[float] = None
        self.iteration = 0

        # Controller-owned runtime handles (not serialized).
        self.actor = None
        self.future = None

    @property
    def local_dir(self) -> str:
        d = os.path.join(self.experiment_dir, f"trial_{self.trial_id}")
        os.makedirs(d, exist_ok=True)
        return d

    def set_status(self, status: str) -> None:
        self.status = status
        if status == Trial.RUNNING and self.start_time is None:
            self.start_time = time.time()

    def should_recover(self) -> bool:
        return self.num_failures <= self.max_failures

    def metadata(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "trainable_name": self.trainable_name,
            "config": _jsonable(self.config),
            "status": self.status,
            "iteration": self.iteration,
            "last_result": _jsonable(self.last_result),
            "error": self.error_msg,
        }

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, it={self.iteration})"


def _jsonable(obj: Any) -> Any:
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)
