"""Head-node web dashboard: JSON state APIs + one static HTML page.

The reference ships a 25k-line aiohttp + React dashboard
(dashboard/head.py:200-215 autoloads module subclasses; the TS frontend
renders GCS state). Everything it displays already exists here as Python
state — controller tables, task events, the log buffer, prometheus text —
so the TPU-native dashboard is a thin read-only HTTP layer over those
sources plus a single self-contained HTML page (no build step, no node_modules;
the page polls the JSON endpoints).

Endpoints:
  GET /                      HTML overview (auto-refreshing)
  GET /api/cluster           summary: nodes, resources, job, uptime
  GET /api/nodes             state API list_nodes
  GET /api/tasks[?limit=]    state API list_tasks
  GET /api/actors            state API list_actors
  GET /api/objects           state API list_objects
  GET /api/placement_groups  state API list_placement_groups
  GET /api/task_summary      per-(name,state) counts
  GET /api/logs[?node_id=&wid=&after_seq=&limit=]   log buffer tail
  GET /api/timeline          chrome://tracing JSON of task events + buffered
                             tracing spans (serving + training rows)
  GET /api/metrics_history[?limit=&since=]   gauge-suite timeseries ring
  GET /api/llm[?steps=]      LLM engine panel: stats, flight recorder,
                             dead letters, shed ring + overload counters,
                             per named engine actor
  GET /api/fleet[?steps=]    fleet observability: per-replica time ledger
                             (host-schedule/device/commit/fabric/idle
                             decomposition of step wall), goodput, MFU,
                             merged cross-replica request histograms +
                             percentiles (observability.fleet_snapshot)
  GET /api/serve             Serve control-plane panel: per-deployment
                             replica lifecycle states (STARTING/RUNNING/
                             DRAINING), transition history, drain durations,
                             drained/migrated counts, autoscaling signals
  GET /api/train[?rounds=]   training-run panel: round records, per-phase
                             breakdown, straggler flags, per recent fit()
  GET /metrics               prometheus text exposition (runtime gauges,
                             LLM engine gauges, AND serve replica-state
                             gauges refreshed at scrape time)
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_START = time.time()

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray-tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.4rem}
 table{border-collapse:collapse;width:100%;background:#fff;font-size:.85rem}
 th,td{border:1px solid #ddd;padding:.3rem .5rem;text-align:left}
 th{background:#f0f0f0} .mono{font-family:ui-monospace,monospace}
 #cluster{background:#fff;border:1px solid #ddd;padding:.6rem 1rem}
 .ok{color:#0a7d33}.bad{color:#c22}
</style></head><body>
<h1>ray-tpu dashboard</h1>
<div id="cluster">loading…</div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Task summary</h2><table id="tasks"></table>
<h2>Serve deployments</h2><div id="serve">none</div>
<h2>LLM engines</h2><div id="llm">none</div>
<h2>Fleet ledger</h2><div id="fleet">none</div>
<h2>Train runs</h2><div id="train">none</div>
<h2>History <span id="hist_legend" style="font-size:.75rem;font-weight:normal"></span></h2>
<canvas id="hist" width="900" height="160"
  style="background:#fff;border:1px solid #ddd;width:100%;max-width:900px"></canvas>
<h2>Recent logs</h2><pre id="logs" class="mono"
  style="background:#fff;border:1px solid #ddd;padding:.6rem;max-height:20rem;overflow:auto"></pre>
<script>
const HIST_KEYS=[['tasks:RUNNING','#0a7d33'],['scheduler_queued','#c22'],
                 ['object_store_used','#1565c0']];
function drawHistory(samples){
  const cv=document.getElementById('hist'),ctx=cv.getContext('2d');
  ctx.clearRect(0,0,cv.width,cv.height);
  if(!samples.length)return;
  document.getElementById('hist_legend').innerHTML=HIST_KEYS.map(
    ([k,c])=>`<span style="color:${c}">■ ${esc(k)}</span>`).join(' ');
  for(const [key,color] of HIST_KEYS){
    const ys=samples.map(s=>s.v[key]??0);
    const max=Math.max(...ys,1e-9);
    ctx.strokeStyle=color;ctx.beginPath();
    ys.forEach((y,i)=>{
      const px=i*(cv.width-10)/Math.max(ys.length-1,1)+5;
      const py=cv.height-8-(y/max)*(cv.height-16);
      i?ctx.lineTo(px,py):ctx.moveTo(px,py);
    });
    ctx.stroke();
  }
}
async function j(u){const r=await fetch(u);return r.json()}
function renderLLM(engines){
  const el=document.getElementById('llm');
  if(!engines.length){el.textContent='none';return}
  el.innerHTML=engines.map(e=>{
    if(e.error)return `<p><b>${esc(e.name)}</b> <span class=bad>${esc(e.error)}</span></p>`;
    const m=e.metrics,fr=e.flight_record;
    const head=`<p><b class=mono>${esc(e.name)}</b> · `+
      `${m.wedged?'<span class=bad>WEDGED</span>':'<span class=ok>healthy</span>'} · `+
      ((m.tensor_parallel_size||1)>1?`tp ${m.tensor_parallel_size} · `+
        `pool ${(m.kv_pool_bytes_per_shard/1048576).toFixed(1)}MiB/chip `+
        `(${(m.kv_pool_bytes/1048576).toFixed(1)} total) · `:'')+
      `steps ${m.steps} · decode tok ${m.decode_tokens} · `+
      `occupancy ${(m.mean_occupancy??0).toFixed(2)} · `+
      `cache ${(m.cache_utilization??0).toFixed(2)} · `+
      `hit rate ${(m.prefix_cache_hit_rate??0).toFixed(2)} · `+
      `queue ${m.queue_depth} · preempt ${m.num_preemptions} · `+
      `dead letters ${m.num_dead_letters}`+
      ((m.shed_requests||m.expired_requests||m.fabric_timeouts)?
        ` · <span class=bad>shed ${m.shed_requests??0}</span>`+
        ` · expired ${m.expired_requests??0}`+
        (m.fabric_timeouts?` · fabric timeouts ${m.fabric_timeouts}`:''):'')+
      (m.async_scheduling?` · <b>async</b> host gap `+
        `${m.host_gap_mean_s==null?'—':(1e6*m.host_gap_mean_s).toFixed(0)+'µs'} mean`+
        ((e.latency_percentiles?.host_gap_s?.p50)!=null?
          ` / ${(1e6*e.latency_percentiles.host_gap_s.p50).toFixed(0)}µs p50`:'')+
        ` · inflight ${m.inflight_steps}`:'')+`</p>`+
      (m.kv_fabric&&m.kv_fabric!=='off'?
        `<p style="font-size:.8rem">kv fabric <b class=mono>${esc(m.kv_fabric)}</b>`+
        (m.engine_role&&m.engine_role!=='unified'?` (${esc(m.engine_role)} role)`:'')+
        ` · hit rate ${(m.fabric_hit_rate??0).toFixed(2)} · `+
        `spilled ${m.fabric_spill_blocks} / restored ${m.fabric_restore_blocks} blocks · `+
        `store ${((m.fabric_store?.bytes_used??0)/1048576).toFixed(1)}/`+
        `${((m.fabric_store?.byte_budget??0)/1048576).toFixed(1)}MiB `+
        `(${m.fabric_store?.num_blocks??0} blocks, ${m.fabric_store?.evictions??0} evictions)</p>`:'');
    const steps=(fr.steps||[]).slice(-12).map(s=>
      `<tr><td>${s.step}</td><td>${esc(s.phase)}${s.chained?'⤳':''}</td><td>${s.batch_size}</td>`+
      `<td>${s.tokens_in}/${s.tokens_out}</td><td>${s.cache_hit_tokens}</td>`+
      `<td>${s.preempted}</td><td>${(1e3*s.duration_s).toFixed(1)}ms</td>`+
      `<td>${s.host_gap_s==null?'—':(1e6*s.host_gap_s).toFixed(0)+'µs'}</td></tr>`).join('');
    const stepTable=steps?`<table><tr><th>step</th><th>phase</th><th>batch</th>`+
      `<th>tok in/out</th><th>cache hits</th><th>preempt</th><th>dur</th><th>gap</th></tr>${steps}</table>`:'';
    const compiles=(fr.compile_events||[]).map(c=>
      `${esc(c.program)}[${c.bucket}] ${c.compile_s.toFixed(2)}s`).join(' · ');
    const fails=(fr.failures||[]).slice(-5).map(f=>
      `<li class=bad>step ${f.step} ${esc(f.action)}: ${esc(f.error)}</li>`).join('');
    const sheds=(e.shed_requests||[]).slice(-5).map(s=>
      `${esc(s.request_id??'?')} ${esc(s.reason??'')} (queue ${s.queue_len??0}, `+
      `retry ${((s.retry_after_s??0)*1e3).toFixed(0)}ms)`).join(' · ');
    return head+stepTable+
      (compiles?`<p style="font-size:.8rem">warmup compiles: ${compiles}</p>`:'')+
      (sheds?`<p style="font-size:.8rem" class=bad>recent sheds: ${sheds}</p>`:'')+
      (fails?`<ul style="font-size:.8rem">${fails}</ul>`:'');
  }).join('<hr>');
}
function renderFleet(f){
  const el=document.getElementById('fleet');
  const reps=Object.entries(f.replicas||{});
  if(!reps.length){el.textContent='none';return}
  const cols=['idle_s','prefill_s','fabric_wait_s','host_schedule_s',
              'device_s','commit_s','other_s','loop_s'];
  const pct=x=>x==null?'—':(100*x).toFixed(1)+'%';
  const rows=reps.map(([name,r])=>{
    if(r.error)return `<tr><td class=mono>${esc(name)}</td>`+
      `<td colspan=${cols.length+4} class=bad>${esc(r.error)}</td></tr>`;
    const L=r.ledger;
    return `<tr><td class=mono>${esc(name)}</td>`+
      `<td>${L.wall_s.toFixed(2)}s</td>`+
      cols.map(c=>`<td>${pct((L.fractions||{})[c])}</td>`).join('')+
      `<td>${pct(L.coverage)}</td>`+
      `<td>${L.goodput_tokens_per_s.toFixed(1)}</td>`+
      `<td>${L.mfu==null?'—':pct(L.mfu)}</td></tr>`;
  }).join('');
  const fl=f.fleet||{};
  const p=f.percentiles||{};
  const pc=(m,q)=>p[m]?.[q]==null?'—':(1e3*p[m][q]).toFixed(1)+'ms';
  el.innerHTML=`<table><tr><th>replica</th><th>wall</th>`+
    cols.map(c=>`<th>${esc(c.replace(/_s$/,''))}</th>`).join('')+
    `<th>Σ/wall</th><th>tok/s</th><th>MFU</th></tr>${rows}</table>`+
    `<p style="font-size:.8rem">fleet: ${fl.replicas??0} replicas · `+
    `${(fl.goodput_tokens_per_s??0).toFixed(1)} tok/s · `+
    `top columns ${(fl.bottlenecks||[]).slice(0,3).map(esc).join(' → ')||'—'} · `+
    `ttft p50/p99 ${pc('llm_request_ttft_seconds','p50')}/${pc('llm_request_ttft_seconds','p99')} · `+
    `e2e p99 ${pc('llm_request_e2e_seconds','p99')}</p>`;
}
function renderServe(apps){
  const el=document.getElementById('serve');
  if(apps.error){el.innerHTML=`<span class=bad>${esc(apps.error)}</span>`;return}
  const rows=[];
  for(const [app,deps] of Object.entries(apps)){
    for(const [dep,d] of Object.entries(deps)){
      const sc=d.state_counts||{};
      const states=['STARTING','RUNNING','DRAINING'].map(s=>{
        const n=sc[s]||0;
        return n?`${s.toLowerCase()} ${s==='DRAINING'?'<span class=bad>'+n+'</span>':n}`:'';
      }).filter(Boolean).join(' · ')||'no replicas';
      const ds=d.drain_seconds||{};
      const hist=(d.history||[]).slice(-6).map(h=>
        `${esc(h.tag.split('#').pop())}:${esc(h.state)}`).join(' → ');
      const sig=d.autoscaling_signals;
      rows.push(`<p><b class=mono>${esc(app)}#${esc(dep)}</b> · `+
        `${d.status==='HEALTHY'?'<span class=ok>HEALTHY</span>':'<span class=bad>'+esc(d.status)+'</span>'} · `+
        `target ${d.target_replicas} · ${states} · `+
        `drained ${d.num_drained_replicas} replicas / ${d.num_migrated_requests} migrated streams`+
        (ds.p50!=null?` · drain p50 ${(ds.p50*1e3).toFixed(0)}ms p99 ${(ds.p99*1e3).toFixed(0)}ms`:'')+
        (sig?`<br><span style="font-size:.8rem">slo window: queue p99 ${sig.queue_time_p99_s==null?'—':(sig.queue_time_p99_s*1e3).toFixed(1)+'ms'} · `+
          `ttft p99 ${sig.ttft_p99_s==null?'—':(sig.ttft_p99_s*1e3).toFixed(1)+'ms'} · `+
          `backlog ${sig.prefill_backlog_tokens} tok</span>`:'')+
        (hist?`<br><span style="font-size:.8rem" class=mono>${hist}</span>`:'')+
        `</p>`);
    }
  }
  el.innerHTML=rows.join('')||'none';
}
function renderTrain(runs){
  const el=document.getElementById('train');
  if(!runs.length){el.textContent='none';return}
  el.innerHTML=runs.map(r=>{
    const ps=r.phase_stats||{};
    const phases=Object.entries(ps).map(([p,s])=>
      `${esc(p)} ${(1e3*s.median).toFixed(1)}ms`).join(' · ');
    const head=`<p><b class=mono>${esc(r.name)}</b> [${esc(r.run_id)}] · `+
      `${r.error?'<span class=bad>'+esc(r.error)+'</span>'
               :(r.finished?'<span class=ok>finished</span>':'running')} · `+
      `${r.num_workers} workers · rounds ${r.rounds_total} · `+
      `samples ${r.samples_total} · `+
      `straggler rounds ${r.straggler_rounds?'<span class=bad>'+r.straggler_rounds+'</span>':'0'}`+
      `</p><p style="font-size:.8rem">phase medians: ${phases||'n/a'}</p>`;
    const rounds=(r.rounds||[]).slice(-8).map(x=>
      `<tr><td>${x.round}</td><td>${(1e3*x.duration_s).toFixed(1)}ms</td>`+
      `<td>${x.samples}</td>`+
      `<td>${Object.entries(x.phase_stats||{}).map(([p,s])=>
          `${esc(p)} ${(1e3*s.max).toFixed(1)}`).join(' ')}</td>`+
      `<td>${(x.stragglers||[]).map(s=>
          `<span class=bad>rank ${s.rank}: ${esc(s.phase)}</span>`).join(' ')||'—'}</td></tr>`).join('');
    const table=rounds?`<table><tr><th>round</th><th>wall</th><th>samples</th>`+
      `<th>phase max (ms)</th><th>stragglers</th></tr>${rounds}</table>`:'';
    return head+table;
  }).join('<hr>');
}
function esc(s){return String(s).replace(/&/g,'&amp;').replace(/</g,'&lt;')
  .replace(/>/g,'&gt;').replace(/"/g,'&quot;')}
function fill(id, rows, cols){
  const t=document.getElementById(id);
  if(!rows.length){t.innerHTML='<tr><td>none</td></tr>';return}
  cols=cols||Object.keys(rows[0]);
  t.innerHTML='<tr>'+cols.map(c=>'<th>'+esc(c)+'</th>').join('')+'</tr>'+
    rows.map(r=>'<tr>'+cols.map(c=>'<td>'+esc(JSON.stringify(r[c]??''))+'</td>').join('')+'</tr>').join('');
}
async function refresh(){
  try{
    const c=await j('/api/cluster');
    document.getElementById('cluster').innerHTML=
      `job <b class=mono>${c.job_id}</b> · ${c.alive_nodes}/${c.nodes} nodes alive · `+
      `uptime ${c.uptime_s.toFixed(0)}s · resources `+
      `<span class=mono>${JSON.stringify(c.resources_available)}</span> / `+
      `<span class=mono>${JSON.stringify(c.resources_total)}</span>`;
    fill('nodes', await j('/api/nodes'),
         ['node_id','state','resources_total','resources_available','is_head_node']);
    fill('actors', await j('/api/actors'),
         ['actor_id','class_name','state','name','num_restarts']);
    const s=await j('/api/task_summary');
    fill('tasks', Object.entries(s).map(([k,v])=>({task:k,count:v})));
    renderServe(await j('/api/serve'));
    renderLLM(await j('/api/llm?steps=12'));
    renderFleet(await j('/api/fleet'));
    renderTrain(await j('/api/train?rounds=8'));
    const logs=await j('/api/logs?limit=200');
    document.getElementById('logs').textContent=
      logs.map(l=>`(pid=${l.pid}, node=${l.hostname}) ${l.line}`).join('\\n');
    drawHistory(await j('/api/metrics_history?limit=720'));
  }catch(e){document.getElementById('cluster').innerHTML=
      '<span class=bad>refresh failed: '+e+'</span>'}
  setTimeout(refresh, 2000);
}
refresh();
</script></body></html>"""


def _serve_snapshot(runtime) -> dict:
    """The controller's replica-lifecycle observability plus drain-duration
    percentiles from the serve_replica_drain_seconds histogram (same
    in-process registry read as the LLM latency panel). Controller
    failures degrade to an error field, never a 500."""
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    existing = runtime.controller.get_named_actor(
        CONTROLLER_NAME, runtime.namespace
    )
    if existing is None:
        return {}
    import ray_tpu
    from ray_tpu.actor import ActorHandle
    from ray_tpu.util.metrics import histogram_percentile

    try:
        obs = ray_tpu.get(
            ActorHandle(
                existing, "ServeControllerActor"
            ).get_observability.remote(),
            timeout=2.0,
        )
    except Exception as exc:
        return {"error": repr(exc)}
    for app_name, deps in obs.items():
        for dep_name, dep in deps.items():
            tags = {"app": app_name, "deployment": dep_name}
            try:
                dep["drain_seconds"] = {
                    "p50": histogram_percentile(
                        "serve_replica_drain_seconds", 50.0, tags
                    ),
                    "p99": histogram_percentile(
                        "serve_replica_drain_seconds", 99.0, tags
                    ),
                }
            except KeyError:
                dep["drain_seconds"] = {"p50": None, "p99": None}
    return obs


def _llm_engines_snapshot(runtime, steps_limit: int = 32) -> list:
    """One row per live named LLM engine actor: metrics(), the tail of the
    flight recorder, and the dead-letter ring. Engine failures degrade to
    an error field on the row, never a 500 on the panel."""
    from ray_tpu.util.runtime_metrics import list_llm_engine_actors

    import ray_tpu

    # One combined RPC per engine, all fired up front and collected
    # against one shared deadline: a busy engine's lock is awaited once,
    # and N engines cost the panel max-of-N, not sum-of-N.
    pending = []
    for name, namespace in list_llm_engine_actors(runtime):
        row = {"name": name}
        try:
            handle = ray_tpu.get_actor(name, namespace=namespace)
            pending.append(
                (row, handle.observability_snapshot.remote(steps_limit))
            )
        except Exception as exc:
            row["error"] = repr(exc)
            pending.append((row, None))
    deadline = time.monotonic() + 2.0
    rows = []
    for row, ref in pending:
        if ref is not None:
            try:
                row.update(
                    ray_tpu.get(
                        ref, timeout=max(deadline - time.monotonic(), 0.05)
                    )
                )
                row["latency_percentiles"] = _llm_latency_percentiles(
                    row.get("metrics", {}).get("engine_id")
                )
            except Exception as exc:
                row["error"] = repr(exc)
        rows.append(row)
    return rows


def _llm_latency_percentiles(engine_id) -> dict:
    """p50/p99 of the serving SLO trio + queue time, interpolated from the
    request histograms the engine already exports (util.metrics
    histogram_percentile — same helper the loadgen SLO gate reads). Engines
    run in-process, so the panel reads the shared registry directly; a
    series that has not observed yet reports null, never an error."""
    from ray_tpu.util.metrics import histogram_percentile

    out: dict = {}
    if engine_id is None:
        return out
    tags = {"engine": engine_id}
    for label, name in (
        ("ttft_s", "llm_request_ttft_seconds"),
        ("tpot_s", "llm_request_time_per_output_token_seconds"),
        ("queue_s", "llm_request_queue_time_seconds"),
        ("e2e_s", "llm_request_e2e_seconds"),
        ("host_gap_s", "llm_engine_step_host_gap_seconds"),
    ):
        try:
            out[label] = {
                "p50": histogram_percentile(name, 50.0, tags),
                "p99": histogram_percentile(name, 99.0, tags),
            }
        except KeyError:
            out[label] = {"p50": None, "p99": None}
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "ray-tpu-dashboard"

    def log_message(self, *args):  # silence per-request stderr noise
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj) -> None:
        self._send(200, json.dumps(obj, default=str).encode(), "application/json")

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            self._route()
        except BrokenPipeError:
            pass
        except Exception as exc:  # surface handler bugs as 500s, not hangs
            try:
                self._send(500, repr(exc).encode(), "text/plain")
            except Exception:
                pass

    def _route(self) -> None:
        from ray_tpu.util.state import api as state
        from ray_tpu.util import metrics

        runtime = self.server.runtime  # type: ignore[attr-defined]
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[-1] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        path = parsed.path
        limit = int(q.get("limit", 1000))
        if path == "/":
            self._send(200, _PAGE.encode(), "text/html")
        elif path == "/api/cluster":
            nodes = list(runtime.controller.nodes.values())
            total: dict = {}
            avail: dict = {}
            for node in nodes:
                for key, val in node.total.items():
                    total[key] = total.get(key, 0) + val
                for key, val in node.available.items():
                    avail[key] = avail.get(key, 0) + val
            self._json(
                {
                    "job_id": runtime.job_id.hex(),
                    "nodes": len(nodes),
                    "alive_nodes": sum(node.alive for node in nodes),
                    "resources_total": total,
                    "resources_available": avail,
                    "uptime_s": time.time() - _START,
                }
            )
        elif path == "/api/nodes":
            self._json(state.list_nodes(limit=limit))
        elif path == "/api/tasks":
            self._json(state.list_tasks(limit=limit))
        elif path == "/api/actors":
            self._json(state.list_actors(limit=limit))
        elif path == "/api/objects":
            self._json(state.list_objects(limit=limit))
        elif path == "/api/placement_groups":
            self._json(state.list_placement_groups(limit=limit))
        elif path == "/api/task_summary":
            self._json(state.summarize_tasks())
        elif path == "/api/logs":
            self._json(
                runtime.logs.tail(
                    node_id=q.get("node_id"),
                    wid=int(q["wid"]) if "wid" in q else None,
                    after_seq=int(q["after_seq"]) if "after_seq" in q else None,
                    limit=limit,
                )
            )
        elif path == "/api/timeline":
            from ray_tpu.util import tracing

            self._json(
                runtime.task_events.chrome_trace()
                + tracing.chrome_spans(runtime)
            )
        elif path == "/api/traces":
            from ray_tpu.util import tracing

            self._json(
                tracing.traces(trace_id=q.get("trace_id"), runtime=runtime)
            )
        elif path == "/api/metrics_history":
            sampler = getattr(runtime, "_metrics_sampler", None)
            history = getattr(sampler, "history", None)
            self._json(
                history.snapshot(
                    limit=min(limit, 720), since=float(q.get("since", 0))
                )
                if history is not None
                else []
            )
        elif path == "/api/llm":
            self._json(
                _llm_engines_snapshot(
                    runtime, steps_limit=int(q.get("steps", 32))
                )
            )
        elif path == "/api/fleet":
            from ray_tpu.observability import fleet_snapshot

            self._json(
                fleet_snapshot(
                    runtime, steps_limit=int(q.get("steps", 512))
                )
            )
        elif path == "/api/serve":
            self._json(_serve_snapshot(runtime))
        elif path == "/api/train":
            from ray_tpu.train.observability import list_runs

            self._json(
                list_runs(
                    limit=int(q.get("limit", 8)),
                    rounds_limit=int(q.get("rounds", 8)),
                )
            )
        elif path == "/metrics":
            from ray_tpu.util.runtime_metrics import (
                sample_llm_engine_metrics,
                sample_runtime_metrics,
                sample_serve_metrics,
            )

            sample_runtime_metrics(runtime)  # scrape-time freshness
            sample_llm_engine_metrics(runtime)  # idle engines stay current
            sample_serve_metrics(runtime)  # replica lifecycle-state gauges
            self._send(200, metrics.prometheus_text().encode(), "text/plain")
        else:
            self._send(404, b"not found", "text/plain")


class DashboardServer:
    """Threaded HTTP server bound to the head; read-only over runtime state."""

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 8265):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.runtime = runtime  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dashboard", daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


def start_dashboard(runtime, host: str = "127.0.0.1", port: int = 8265) -> DashboardServer:
    return DashboardServer(runtime, host, port)
