"""Head-node web dashboard: JSON state APIs + one static HTML page.

The reference ships a 25k-line aiohttp + React dashboard
(dashboard/head.py:200-215 autoloads module subclasses; the TS frontend
renders GCS state). Everything it displays already exists here as Python
state — controller tables, task events, the log buffer, prometheus text —
so the TPU-native dashboard is a thin read-only HTTP layer over those
sources plus a single self-contained HTML page (no build step, no node_modules;
the page polls the JSON endpoints).

Endpoints:
  GET /                      HTML overview (auto-refreshing)
  GET /api/cluster           summary: nodes, resources, job, uptime
  GET /api/nodes             state API list_nodes
  GET /api/tasks[?limit=]    state API list_tasks
  GET /api/actors            state API list_actors
  GET /api/objects           state API list_objects
  GET /api/placement_groups  state API list_placement_groups
  GET /api/task_summary      per-(name,state) counts
  GET /api/logs[?node_id=&wid=&after_seq=&limit=]   log buffer tail
  GET /api/timeline          chrome://tracing JSON of task events
  GET /api/metrics_history[?limit=&since=]   gauge-suite timeseries ring
  GET /metrics               prometheus text exposition
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_START = time.time()

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray-tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.4rem}
 table{border-collapse:collapse;width:100%;background:#fff;font-size:.85rem}
 th,td{border:1px solid #ddd;padding:.3rem .5rem;text-align:left}
 th{background:#f0f0f0} .mono{font-family:ui-monospace,monospace}
 #cluster{background:#fff;border:1px solid #ddd;padding:.6rem 1rem}
 .ok{color:#0a7d33}.bad{color:#c22}
</style></head><body>
<h1>ray-tpu dashboard</h1>
<div id="cluster">loading…</div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Task summary</h2><table id="tasks"></table>
<h2>History <span id="hist_legend" style="font-size:.75rem;font-weight:normal"></span></h2>
<canvas id="hist" width="900" height="160"
  style="background:#fff;border:1px solid #ddd;width:100%;max-width:900px"></canvas>
<h2>Recent logs</h2><pre id="logs" class="mono"
  style="background:#fff;border:1px solid #ddd;padding:.6rem;max-height:20rem;overflow:auto"></pre>
<script>
const HIST_KEYS=[['tasks:RUNNING','#0a7d33'],['scheduler_queued','#c22'],
                 ['object_store_used','#1565c0']];
function drawHistory(samples){
  const cv=document.getElementById('hist'),ctx=cv.getContext('2d');
  ctx.clearRect(0,0,cv.width,cv.height);
  if(!samples.length)return;
  document.getElementById('hist_legend').innerHTML=HIST_KEYS.map(
    ([k,c])=>`<span style="color:${c}">■ ${esc(k)}</span>`).join(' ');
  for(const [key,color] of HIST_KEYS){
    const ys=samples.map(s=>s.v[key]??0);
    const max=Math.max(...ys,1e-9);
    ctx.strokeStyle=color;ctx.beginPath();
    ys.forEach((y,i)=>{
      const px=i*(cv.width-10)/Math.max(ys.length-1,1)+5;
      const py=cv.height-8-(y/max)*(cv.height-16);
      i?ctx.lineTo(px,py):ctx.moveTo(px,py);
    });
    ctx.stroke();
  }
}
async function j(u){const r=await fetch(u);return r.json()}
function esc(s){return String(s).replace(/&/g,'&amp;').replace(/</g,'&lt;')
  .replace(/>/g,'&gt;').replace(/"/g,'&quot;')}
function fill(id, rows, cols){
  const t=document.getElementById(id);
  if(!rows.length){t.innerHTML='<tr><td>none</td></tr>';return}
  cols=cols||Object.keys(rows[0]);
  t.innerHTML='<tr>'+cols.map(c=>'<th>'+esc(c)+'</th>').join('')+'</tr>'+
    rows.map(r=>'<tr>'+cols.map(c=>'<td>'+esc(JSON.stringify(r[c]??''))+'</td>').join('')+'</tr>').join('');
}
async function refresh(){
  try{
    const c=await j('/api/cluster');
    document.getElementById('cluster').innerHTML=
      `job <b class=mono>${c.job_id}</b> · ${c.alive_nodes}/${c.nodes} nodes alive · `+
      `uptime ${c.uptime_s.toFixed(0)}s · resources `+
      `<span class=mono>${JSON.stringify(c.resources_available)}</span> / `+
      `<span class=mono>${JSON.stringify(c.resources_total)}</span>`;
    fill('nodes', await j('/api/nodes'),
         ['node_id','state','resources_total','resources_available','is_head_node']);
    fill('actors', await j('/api/actors'),
         ['actor_id','class_name','state','name','num_restarts']);
    const s=await j('/api/task_summary');
    fill('tasks', Object.entries(s).map(([k,v])=>({task:k,count:v})));
    const logs=await j('/api/logs?limit=200');
    document.getElementById('logs').textContent=
      logs.map(l=>`(pid=${l.pid}, node=${l.hostname}) ${l.line}`).join('\\n');
    drawHistory(await j('/api/metrics_history?limit=720'));
  }catch(e){document.getElementById('cluster').innerHTML=
      '<span class=bad>refresh failed: '+e+'</span>'}
  setTimeout(refresh, 2000);
}
refresh();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "ray-tpu-dashboard"

    def log_message(self, *args):  # silence per-request stderr noise
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj) -> None:
        self._send(200, json.dumps(obj, default=str).encode(), "application/json")

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            self._route()
        except BrokenPipeError:
            pass
        except Exception as exc:  # surface handler bugs as 500s, not hangs
            try:
                self._send(500, repr(exc).encode(), "text/plain")
            except Exception:
                pass

    def _route(self) -> None:
        from ray_tpu.util.state import api as state
        from ray_tpu.util import metrics

        runtime = self.server.runtime  # type: ignore[attr-defined]
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[-1] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        path = parsed.path
        limit = int(q.get("limit", 1000))
        if path == "/":
            self._send(200, _PAGE.encode(), "text/html")
        elif path == "/api/cluster":
            nodes = list(runtime.controller.nodes.values())
            total: dict = {}
            avail: dict = {}
            for node in nodes:
                for key, val in node.total.items():
                    total[key] = total.get(key, 0) + val
                for key, val in node.available.items():
                    avail[key] = avail.get(key, 0) + val
            self._json(
                {
                    "job_id": runtime.job_id.hex(),
                    "nodes": len(nodes),
                    "alive_nodes": sum(node.alive for node in nodes),
                    "resources_total": total,
                    "resources_available": avail,
                    "uptime_s": time.time() - _START,
                }
            )
        elif path == "/api/nodes":
            self._json(state.list_nodes(limit=limit))
        elif path == "/api/tasks":
            self._json(state.list_tasks(limit=limit))
        elif path == "/api/actors":
            self._json(state.list_actors(limit=limit))
        elif path == "/api/objects":
            self._json(state.list_objects(limit=limit))
        elif path == "/api/placement_groups":
            self._json(state.list_placement_groups(limit=limit))
        elif path == "/api/task_summary":
            self._json(state.summarize_tasks())
        elif path == "/api/logs":
            self._json(
                runtime.logs.tail(
                    node_id=q.get("node_id"),
                    wid=int(q["wid"]) if "wid" in q else None,
                    after_seq=int(q["after_seq"]) if "after_seq" in q else None,
                    limit=limit,
                )
            )
        elif path == "/api/timeline":
            self._json(runtime.task_events.chrome_trace())
        elif path == "/api/traces":
            from ray_tpu.util import tracing

            self._json(
                tracing.traces(trace_id=q.get("trace_id"), runtime=runtime)
            )
        elif path == "/api/metrics_history":
            sampler = getattr(runtime, "_metrics_sampler", None)
            history = getattr(sampler, "history", None)
            self._json(
                history.snapshot(
                    limit=min(limit, 720), since=float(q.get("since", 0))
                )
                if history is not None
                else []
            )
        elif path == "/metrics":
            from ray_tpu.util.runtime_metrics import sample_runtime_metrics

            sample_runtime_metrics(runtime)  # scrape-time freshness
            self._send(200, metrics.prometheus_text().encode(), "text/plain")
        else:
            self._send(404, b"not found", "text/plain")


class DashboardServer:
    """Threaded HTTP server bound to the head; read-only over runtime state."""

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 8265):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.runtime = runtime  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dashboard", daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


def start_dashboard(runtime, host: str = "127.0.0.1", port: int = 8265) -> DashboardServer:
    return DashboardServer(runtime, host, port)
