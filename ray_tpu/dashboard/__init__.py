from ray_tpu.dashboard.head import DashboardServer, start_dashboard

__all__ = ["DashboardServer", "start_dashboard"]
