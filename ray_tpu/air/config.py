"""Run/scaling/failure/checkpoint configs (reference: python/ray/air/config.py —
ScalingConfig :91, RunConfig :704, FailureConfig :523, CheckpointConfig :574).

TPU-first deltas: ScalingConfig speaks chips and hosts (`num_workers` = TPU
*hosts*, one worker process per host — SURVEY.md CS4 TPU translation), and
`chips_per_worker` replaces `use_gpu`/`resources_per_worker` GPU counts.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    # TPU chips each worker (host) drives; 0 = CPU-only training.
    chips_per_worker: int = 0
    cpus_per_worker: float = 1.0
    resources_per_worker: dict[str, float] = field(default_factory=dict)
    # Placement strategy for the worker bundles: a TPU slice is an atomic
    # multi-host gang, so chips default to STRICT_SPREAD (one bundle per host).
    placement_strategy: Optional[str] = None

    @property
    def use_tpu(self) -> bool:
        return self.chips_per_worker > 0

    def bundle_specs(self) -> list[dict[str, float]]:
        bundle: dict[str, float] = {"CPU": float(self.cpus_per_worker)}
        if self.chips_per_worker:
            bundle["TPU"] = float(self.chips_per_worker)
        bundle.update(self.resources_per_worker)
        return [dict(bundle) for _ in range(self.num_workers)]

    def strategy(self) -> str:
        if self.placement_strategy:
            return self.placement_strategy
        return "STRICT_SPREAD" if self.use_tpu and self.num_workers > 1 else "PACK"

    @property
    def total_chips(self) -> int:
        return self.num_workers * self.chips_per_worker


@dataclass
class FailureConfig:
    # Number of worker-group restarts allowed; -1 = unlimited.
    max_failures: int = 0


@dataclass
class TrainConfig:
    """Training-plane knobs (the `EngineConfig.instrument` mirror).

    instrument: per-round step profiling, `train.*` spans, `train_*`
        histograms, straggler detection, and the run registry the dashboard
        `/api/train` panel reads. Off compiles the whole plane out of the
        report path (sessions get no profiler, hooks see None).
    straggler_factor/straggler_min_s: a rank is flagged when its non-report
        work time exceeds the low-median across ranks by `straggler_factor`
        AND by at least `straggler_min_s` (absolute floor so near-zero
        rounds don't flag on noise).
    profiler_capacity: per-worker round-record ring size.
    rounds_capacity: driver-side per-run round-record ring size.
    """

    instrument: bool = True
    straggler_factor: float = 2.0
    straggler_min_s: float = 0.05
    profiler_capacity: int = 512
    rounds_capacity: int = 256


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # "max" | "min"
    checkpoint_at_end: bool = True
    checkpoint_frequency: int = 0

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    # Tune stopping criteria, e.g. {"training_iteration": 10}.
    stop: Optional[dict] = None
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results"
        )
        return os.path.join(base, self.name) if self.name else base
