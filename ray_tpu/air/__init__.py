from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "session",
]
