"""Training session API — what user train loops call.

Reference: air/session.py (report :43, get_checkpoint :97, get_dataset_shard
:359) backed by train/_internal/session.py's rendezvous queue (:76,:421): each
worker runs the user loop on a runner thread; `report` blocks until the driver
consumes the result, which is what makes scheduler-driven early stopping (ASHA
kill mid-epoch) safe.

The active session lives in thread-local state set by the worker runner.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from ray_tpu.air.checkpoint import Checkpoint

_TL = threading.local()


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    trial_name: str = ""
    trial_id: str = ""
    # Devices/mesh info installed by the backend (JaxBackend).
    devices: Any = None
    mesh: Any = None
    extras: dict = field(default_factory=dict)


class _Session:
    """One per worker-runner thread."""

    FINISHED = object()

    def __init__(self, context: TrainContext, checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[dict] = None):
        self.context = context
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        # 1-deep rendezvous: report() blocks until the driver consumes.
        self.result_queue: "queue.Queue" = queue.Queue(maxsize=1)
        self.stop_event = threading.Event()

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint]) -> None:
        if self.stop_event.is_set():
            raise StopIteration("Training stopped by the driver")
        self.result_queue.put({"metrics": dict(metrics), "checkpoint": checkpoint})
        if self.stop_event.is_set():
            raise StopIteration("Training stopped by the driver")

    def finish(self) -> None:
        self.result_queue.put(self.FINISHED)


def _set_session(session: Optional[_Session]) -> None:
    _TL.session = session


def _get_session() -> Optional[_Session]:
    return getattr(_TL, "session", None)


def _require_session() -> _Session:
    session = _get_session()
    if session is None:
        raise RuntimeError(
            "No training session active; this API must be called inside a "
            "train_loop_per_worker"
        )
    return session


# -- public API --------------------------------------------------------------


def report(metrics: dict, *, checkpoint: Optional[Checkpoint] = None) -> None:
    _require_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _require_session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    shards = _require_session().dataset_shards
    if name not in shards:
        raise KeyError(f"No dataset shard named {name!r}; have {list(shards)}")
    return shards[name]


def get_world_rank() -> int:
    return _require_session().context.world_rank


def get_world_size() -> int:
    return _require_session().context.world_size


def get_local_rank() -> int:
    return _require_session().context.local_rank


def get_context() -> TrainContext:
    return _require_session().context


def get_mesh():
    """The device mesh the backend formed for this worker (JaxTrainer)."""
    return _require_session().context.mesh
