"""Training session API — what user train loops call.

Reference: air/session.py (report :43, get_checkpoint :97, get_dataset_shard
:359) backed by train/_internal/session.py's rendezvous queue (:76,:421): each
worker runs the user loop on a runner thread; `report` blocks until the driver
consumes the result, which is what makes scheduler-driven early stopping (ASHA
kill mid-epoch) safe.

The active session lives in thread-local state set by the worker runner.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from ray_tpu.air.checkpoint import Checkpoint

_TL = threading.local()


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    trial_name: str = ""
    trial_id: str = ""
    # Devices/mesh info installed by the backend (JaxBackend).
    devices: Any = None
    mesh: Any = None
    extras: dict = field(default_factory=dict)


class _Session:
    """One per worker-runner thread."""

    FINISHED = object()

    def __init__(self, context: TrainContext, checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[dict] = None, profiler=None):
        self.context = context
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        # train.observability.StepProfiler when TrainConfig.instrument is on;
        # None compiles the telemetry plane out of report()/the hook sites.
        self.profiler = profiler
        # 1-deep rendezvous: report() blocks until the driver consumes.
        self.result_queue: "queue.Queue" = queue.Queue(maxsize=1)
        self.stop_event = threading.Event()

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint]) -> None:
        if self.stop_event.is_set():
            raise StopIteration("Training stopped by the driver")
        item = {"metrics": dict(metrics), "checkpoint": checkpoint}
        profiler = self.profiler
        if profiler is not None:
            # Close the round just before the rendezvous so its record
            # rides this report; the put's blocking time is attributed to
            # the NEXT round's `report` phase (it is that round's start).
            item["profile"] = profiler.end_round()
            t0 = time.perf_counter()
            self.result_queue.put(item)
            profiler.add("report", time.perf_counter() - t0)
        else:
            self.result_queue.put(item)
        if self.stop_event.is_set():
            raise StopIteration("Training stopped by the driver")

    def finish(self) -> None:
        self.result_queue.put(self.FINISHED)


def _set_session(session: Optional[_Session]) -> None:
    _TL.session = session


def _get_session() -> Optional[_Session]:
    return getattr(_TL, "session", None)


def _require_session() -> _Session:
    session = _get_session()
    if session is None:
        raise RuntimeError(
            "No training session active; this API must be called inside a "
            "train_loop_per_worker"
        )
    return session


# -- public API --------------------------------------------------------------


def report(metrics: dict, *, checkpoint: Optional[Checkpoint] = None) -> None:
    _require_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _require_session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    session = _require_session()
    shards = session.dataset_shards
    if name not in shards:
        raise KeyError(f"No dataset shard named {name!r}; have {list(shards)}")
    shard = shards[name]
    # Instrumented sessions see the shard through a data_wait clock; list
    # shards (already materialized, nothing to wait on) pass through.
    if session.profiler is not None and hasattr(shard, "iter_batches"):
        from ray_tpu.train.observability import ProfiledDataIterator

        return ProfiledDataIterator(shard, session.profiler)
    return shard


def get_world_rank() -> int:
    return _require_session().context.world_rank


def get_world_size() -> int:
    return _require_session().context.world_size


def get_local_rank() -> int:
    return _require_session().context.local_rank


def get_context() -> TrainContext:
    return _require_session().context


def get_mesh():
    """The device mesh the backend formed for this worker (JaxTrainer)."""
    return _require_session().context.mesh
