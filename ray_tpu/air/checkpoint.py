"""Uniform checkpoint object (reference: air/checkpoint.py:66 — dict ⇄ directory
⇄ URI forms with lazy conversion).

TPU delta: array leaves in dict checkpoints may be sharded jax.Arrays; they are
gathered/saved per-host with orbax when directory-ified (sharded checkpoint
support lives in train/jax/checkpoint_utils.py)."""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Optional

_PAYLOAD_FILE = "checkpoint.pkl"


class Checkpoint:
    """Either an in-memory dict or a directory on disk; converts lazily."""

    def __init__(
        self,
        data: Optional[dict] = None,
        path: Optional[str] = None,
    ):
        if (data is None) == (path is None):
            raise ValueError("Provide exactly one of data= or path=")
        self._data = data
        self._path = path
        self.id = uuid.uuid4().hex[:12]

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        # Train-profiler hook: checkpoint construction inside an
        # instrumented training session counts as the round's `checkpoint`
        # phase (and is the per-rank fault-injection site train.checkpoint).
        from ray_tpu.train.observability import phase_or_null

        with phase_or_null("checkpoint"):
            return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=path)

    # -- accessors ----------------------------------------------------------

    def to_dict(self) -> dict:
        if self._data is not None:
            return dict(self._data)
        payload = os.path.join(self._path, _PAYLOAD_FILE)
        if os.path.exists(payload):
            with open(payload, "rb") as f:
                return pickle.load(f)
        raise ValueError(
            f"Directory checkpoint at {self._path} has no {_PAYLOAD_FILE}; "
            "use to_directory() / as_directory() for raw-file checkpoints"
        )

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._path is not None:
            if os.path.abspath(self._path) != os.path.abspath(path):
                shutil.copytree(self._path, path, dirs_exist_ok=True)
        else:
            with open(os.path.join(path, _PAYLOAD_FILE), "wb") as f:
                pickle.dump(self._data, f)
        return path

    def as_directory(self):
        """Context manager yielding a directory view."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            if self._path is not None:
                yield self._path
            else:
                tmp = self.to_directory()
                try:
                    yield tmp
                finally:
                    shutil.rmtree(tmp, ignore_errors=True)

        return cm()

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._path}"
        return f"Checkpoint({kind})"
