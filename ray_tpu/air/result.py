"""Training/tuning result (reference: air/result.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: dict = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: Optional[str] = None
    metrics_history: list[dict] = field(default_factory=list)
    config: dict = field(default_factory=dict)
    # Training telemetry snapshot (TrainConfig.instrument): per-phase
    # min/median/max across ranks, round records, straggler report. None
    # when instrumentation is off or the trainer doesn't profile.
    train_report: Optional[dict] = None

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint
