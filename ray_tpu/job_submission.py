"""Job submission — run driver scripts as supervised subprocesses.

Reference: dashboard/modules/job/ — JobManager (job_manager.py:508) spawns a
detached JobSupervisor actor per job which execs the user's entrypoint as a
fate-shared subprocess, streams logs to files, and records status in the GCS
KV; JobSubmissionClient (sdk.py:40) is the user surface. Here the supervisor
is a detached-equivalent actor on the in-process runtime; the entrypoint runs
as a real subprocess with its own runtime (the in-process analog of a driver
connecting to the cluster), logs land in a per-job file, and status lives in
the controller KV so every API reads the same source of truth.
"""

from __future__ import annotations

import json
import os
import subprocess
import tempfile
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu

# Job status values (reference: job_submission/__init__.py JobStatus).
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"

_KV_PREFIX = b"job:"


@dataclass
class JobDetails:
    job_id: str
    entrypoint: str
    status: str = PENDING
    message: str = ""
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    log_path: str = ""


@ray_tpu.remote
class JobSupervisor:
    """One per job: runs the entrypoint subprocess and updates KV status."""

    def __init__(self, job_id: str, entrypoint: str, runtime_env: dict, log_path: str):
        import threading

        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._stopped = False
        self._lock = threading.Lock()

    def run(self) -> str:
        from ray_tpu._private.runtime import get_runtime

        kv = get_runtime().controller
        env = dict(os.environ)
        env.update(self.runtime_env.get("env_vars", {}))
        env["RAY_TPU_JOB_ID"] = self.job_id
        cwd = self.runtime_env.get("working_dir") or None
        _update(kv, self.job_id, status=RUNNING, start_time=time.time())
        with open(self.log_path, "ab") as logf:
            # Spawn under the lock so stop() either sees the process or
            # prevents the spawn — never a stop that kills nothing while the
            # entrypoint still launches and runs to completion.
            with self._lock:
                if self._stopped:
                    _update(kv, self.job_id, status=STOPPED, end_time=time.time())
                    return STOPPED
                proc = self.proc = subprocess.Popen(
                    self.entrypoint,
                    shell=True,
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                    env=env,
                    cwd=cwd,
                    start_new_session=True,
                )
            returncode = proc.wait()
        # Under the lock: stop() publishes _stopped before killing the
        # process group, so a wait() woken by that kill must classify as
        # STOPPED, never FAILED-with-SIGTERM.
        with self._lock:
            stopped = self._stopped
        if stopped:
            _update(kv, self.job_id, status=STOPPED, end_time=time.time())
            return STOPPED
        if returncode == 0:
            _update(kv, self.job_id, status=SUCCEEDED, end_time=time.time())
            return SUCCEEDED
        _update(
            kv,
            self.job_id,
            status=FAILED,
            message=f"entrypoint exited with code {returncode}",
            end_time=time.time(),
        )
        return FAILED

    def stop(self) -> bool:
        """Request the job stop. Returns True if the job will not run to
        completion (process killed, or spawn prevented)."""
        import signal

        with self._lock:
            self._stopped = True
            proc = self.proc
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
            return True
        # Not spawned yet: run() will observe _stopped and skip the spawn.
        return proc is None

    def ping(self) -> str:
        return "pong"


def _store(controller, details: JobDetails) -> None:
    controller.kv_put(
        _KV_PREFIX + details.job_id.encode(),
        json.dumps(details.__dict__).encode(),
    )


def _load(controller, job_id: str) -> Optional[JobDetails]:
    raw = controller.kv_get(_KV_PREFIX + job_id.encode())
    if raw is None:
        return None
    return JobDetails(**json.loads(raw))


def _update(controller, job_id: str, **updates) -> None:
    details = _load(controller, job_id)
    if details is None:
        return
    for k, v in updates.items():
        setattr(details, k, v)
    _store(controller, details)


class JobSubmissionClient:
    """User surface (reference sdk.py:40: submit/stop/status/logs/list)."""

    def __init__(self, address: Optional[str] = None):
        from ray_tpu._private.runtime import get_runtime

        self._runtime = get_runtime()
        self._supervisors: Dict[str, Any] = {}
        self._runs: Dict[str, Any] = {}
        self._log_dir = os.path.join(tempfile.gettempdir(), "ray_tpu_job_logs")
        os.makedirs(self._log_dir, exist_ok=True)

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        if _load(self._runtime.controller, job_id) is not None:
            raise ValueError(f"Job {job_id!r} already exists")
        log_path = os.path.join(self._log_dir, f"{job_id}.log")
        details = JobDetails(
            job_id=job_id,
            entrypoint=entrypoint,
            metadata=metadata or {},
            runtime_env=runtime_env or {},
            log_path=log_path,
        )
        _store(self._runtime.controller, details)
        supervisor = JobSupervisor.options(
            name=f"_job_supervisor:{job_id}", num_cpus=0, max_concurrency=4
        ).remote(job_id, entrypoint, runtime_env or {}, log_path)
        self._supervisors[job_id] = supervisor
        self._runs[job_id] = supervisor.run.remote()
        return job_id

    def get_job_status(self, job_id: str) -> str:
        details = _load(self._runtime.controller, job_id)
        if details is None:
            raise KeyError(f"No such job {job_id!r}")
        return details.status

    def get_job_info(self, job_id: str) -> JobDetails:
        details = _load(self._runtime.controller, job_id)
        if details is None:
            raise KeyError(f"No such job {job_id!r}")
        return details

    def get_job_logs(self, job_id: str) -> str:
        details = self.get_job_info(job_id)
        if details.log_path and os.path.exists(details.log_path):
            with open(details.log_path, "r", errors="replace") as f:
                return f.read()
        return ""

    def list_jobs(self) -> List[JobDetails]:
        out = []
        for key in self._runtime.controller.kv_keys(_KV_PREFIX):
            job_id = key[len(_KV_PREFIX) :].decode()
            details = _load(self._runtime.controller, job_id)
            if details is not None:
                out.append(details)
        return sorted(out, key=lambda d: d.start_time or 0)

    def stop_job(self, job_id: str) -> bool:
        supervisor = self._supervisors.get(job_id)
        if supervisor is None:
            raise KeyError(f"No supervisor for job {job_id!r} in this client")
        return ray_tpu.get(supervisor.stop.remote(), timeout=10.0)

    def wait_until_finish(
        self, job_id: str, timeout: float = 300.0, poll_s: float = 0.2
    ) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (SUCCEEDED, FAILED, STOPPED):
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"Job {job_id} still {status} after {timeout}s")

    def delete_job(self, job_id: str) -> bool:
        details = _load(self._runtime.controller, job_id)
        if details is None:
            return False
        if details.status in (PENDING, RUNNING):
            raise RuntimeError("Stop the job before deleting it")
        self._runtime.controller.kv_del(_KV_PREFIX + job_id.encode())
        self._supervisors.pop(job_id, None)
        return True
