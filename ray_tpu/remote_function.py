"""@remote functions (reference: python/ray/remote_function.py, _remote :245)."""

from __future__ import annotations

import functools
from typing import Any, Callable

from ray_tpu._private import options as option_utils
from ray_tpu._private.runtime import get_runtime


class RemoteFunction:
    def __init__(self, func: Callable, task_options: dict[str, Any]):
        self._function = func
        self._options = option_utils.validate_task_options(task_options)
        functools.update_wrapper(self, func)

    def options(self, **task_options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(task_options)
        return RemoteFunction(self._function, merged)

    def remote(self, *args, **kwargs):
        opts = self._options
        runtime = get_runtime()
        resources = option_utils.to_resource_request(
            opts.get("num_cpus"),
            opts.get("num_gpus"),
            opts.get("num_tpus"),
            opts.get("resources"),
            default_num_cpus=1.0,  # tasks default to 1 CPU (ray_option_utils.py)
        )
        num_returns = opts.get("num_returns", 1)
        refs = runtime.submit_task(
            self._function,
            args,
            kwargs,
            name=opts.get("name") or self._function.__qualname__,
            num_returns=num_returns,
            resources=resources,
            scheduling_strategy=opts.get("scheduling_strategy"),
            max_retries=opts.get("max_retries", option_utils.DEFAULT_MAX_RETRIES),
            retry_exceptions=opts.get("retry_exceptions", False),
            runtime_env=opts.get("runtime_env"),
        )
        if num_returns == 0:
            return None
        if num_returns == 1 or num_returns == "streaming":
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node instead of submitting (reference:
        dag/function_node.py)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs, {})

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function.__qualname__!r} cannot be called "
            "directly. Use .remote() instead."
        )
