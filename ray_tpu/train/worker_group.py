"""WorkerGroup — N training-worker actors in a placement group.

Reference: train/_internal/worker_group.py:100 (WorkerGroup), :18
(RayTrainWorker); placement via backend_executor.py:164. The worker actor runs
the user's train loop on a runner thread (train/_internal/session.py:147
RunnerThread) and serves `next_result` from the rendezvous queue.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.air.session import TrainContext, _Session, _set_session
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


@ray_tpu.remote
class RayTrainWorker:
    """One training worker. Methods are called by the BackendExecutor."""

    def __init__(self, context_kwargs: dict):
        self.context = TrainContext(**context_kwargs)
        self.session: Optional[_Session] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[dict] = None
        self.profiler = None  # StepProfiler while instrumented training runs

    # -- backend hooks -------------------------------------------------------

    def run_fn(self, fn: Callable, *args, **kwargs):
        """Execute an arbitrary function on the worker (backend setup)."""
        return fn(self.context, *args, **kwargs)

    def get_context(self) -> dict:
        return {
            "world_rank": self.context.world_rank,
            "world_size": self.context.world_size,
            "local_rank": self.context.local_rank,
            "node_rank": self.context.node_rank,
        }

    # -- training ------------------------------------------------------------

    def start_training(
        self,
        train_fn: Callable,
        config: dict,
        checkpoint,
        dataset_shards: Optional[dict] = None,
        observability: Optional[dict] = None,
    ) -> None:
        profiler = None
        if observability is not None:
            from ray_tpu.train.observability import StepProfiler

            profiler = StepProfiler(
                rank=self.context.world_rank,
                world_size=self.context.world_size,
                trace=observability.get("trace"),
                round_offset=observability.get("round_offset", 0),
                capacity=observability.get("capacity", 512),
            )
        self.profiler = profiler
        session = _Session(
            self.context, checkpoint, dataset_shards, profiler=profiler
        )
        self.session = session
        self._error = None

        def runner():
            _set_session(session)
            try:
                if config:
                    train_fn(config)
                else:
                    try:
                        train_fn({})
                    except TypeError:
                        train_fn()
                session.finish()
            except StopIteration:
                session.finish()
            except BaseException as exc:  # noqa: BLE001
                self._error = {
                    "exception": exc,
                    "traceback": traceback.format_exc(),
                }
                try:
                    session.result_queue.put(session.FINISHED, timeout=1)
                except Exception:
                    pass
            finally:
                _set_session(None)

        self._thread = threading.Thread(target=runner, daemon=True, name="train-runner")
        self._thread.start()

    def next_result(self) -> Optional[dict]:
        """Block for the next report; None when the loop finished. Raises the
        user exception if the loop died (reference: TrainingIterator error
        handling, train/trainer.py:110)."""
        assert self.session is not None, "start_training not called"
        item = self.session.result_queue.get()
        if item is self.session.FINISHED:
            if self._error is not None:
                raise self._error["exception"]
            return None
        return item

    def stop(self) -> None:
        if self.session is not None:
            self.session.stop_event.set()
            # Unblock a report() waiting for a consumer.
            try:
                self.session.result_queue.get_nowait()
            except Exception:
                pass

    def shutdown_check(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def profile_records(self) -> list:
        """This worker's bounded ring of per-round phase records
        (train/observability.StepProfiler); [] when not instrumented."""
        if self.profiler is None:
            return []
        return list(self.profiler.records)


class WorkerGroup:
    """Creates/destroys the actor set + its placement group."""

    def __init__(
        self,
        num_workers: int,
        bundle_specs: list[dict[str, float]],
        strategy: str,
    ):
        self.num_workers = num_workers
        self._pg = placement_group(bundle_specs, strategy=strategy)
        if not self._pg.ready(timeout=60.0):
            raise RuntimeError("Training placement group could not be scheduled")
        bundle_nodes = self._pg.bundle_node_ids()
        # node_rank: distinct nodes in bundle order.
        node_order: dict[str, int] = {}
        self.workers = []
        for rank in range(num_workers):
            node_id = bundle_nodes.get(rank, "")
            node_rank = node_order.setdefault(node_id, len(node_order))
            context_kwargs = dict(
                world_rank=rank,
                world_size=num_workers,
                local_rank=0,
                node_rank=node_rank,
            )
            worker = RayTrainWorker.options(
                num_cpus=0,
                # next_result() blocks awaiting the runner thread; stop() and
                # backend run_fn calls must be able to interleave.
                max_concurrency=8,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg,
                    placement_group_bundle_index=rank,
                ),
                resources={},
            ).remote(context_kwargs)
            self.workers.append(worker)

    def execute(self, fn: Callable, *args, **kwargs) -> list:
        """Run fn(context, *args) on every worker, gather results."""
        return ray_tpu.get(
            [w.run_fn.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=300.0,
        )

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[rank].run_fn.remote(fn, *args, **kwargs), timeout=300.0
        )

    def profile_records(self) -> list[list]:
        """Per-rank round-record rings (rank-indexed)."""
        return ray_tpu.get(
            [w.profile_records.remote() for w in self.workers], timeout=60.0
        )

    @property
    def placement_group(self):
        return self._pg

    def shutdown(self) -> None:
        for worker in self.workers:
            try:
                ray_tpu.kill(worker)
            except Exception:
                pass
        self.workers = []
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass
