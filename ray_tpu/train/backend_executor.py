"""BackendExecutor — drives the worker group through a training run.

Reference: train/_internal/backend_executor.py:45 (placement group :164,
start_training :342, _restart :625). Orchestration only — runs no math.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingWorkerError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
    ):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self.worker_group: Optional[WorkerGroup] = None

    def start(self) -> None:
        try:
            self.worker_group = WorkerGroup(
                num_workers=self._scaling.num_workers,
                bundle_specs=self._scaling.bundle_specs(),
                strategy=self._scaling.strategy(),
            )
            self._backend.on_start(self.worker_group, self._backend_config)
        except Exception as exc:
            # A worker dying while the group forms (e.g. its node was killed
            # between scheduling and startup) is a recoverable group failure:
            # the trainer's FailureConfig loop re-forms on surviving nodes.
            # Tear down whatever partially formed so the retry doesn't leak
            # actors (and the resources they hold).
            self.shutdown()
            raise TrainingWorkerError(f"worker group failed to start: {exc}") from exc

    def start_training(
        self,
        train_fn: Callable,
        config: dict,
        checkpoint: Optional[Checkpoint],
        dataset_shard_fn: Optional[Callable[[int, int], Optional[dict]]] = None,
        observability: Optional[dict] = None,
    ) -> None:
        assert self.worker_group is not None
        self._backend.on_training_start(self.worker_group, self._backend_config)
        refs = []
        for rank, worker in enumerate(self.worker_group.workers):
            shards = (
                dataset_shard_fn(rank, self._scaling.num_workers)
                if dataset_shard_fn
                else None
            )
            refs.append(
                worker.start_training.remote(
                    train_fn, config, checkpoint, shards, observability
                )
            )
        try:
            ray_tpu.get(refs, timeout=300.0)
        except Exception as exc:
            raise TrainingWorkerError(f"training failed to launch: {exc}") from exc

    def next_results(self) -> Optional[list[dict]]:
        """One rendezvous round: every worker's next report, or None when all
        finished. Raises TrainingWorkerError wrapping the first worker error."""
        assert self.worker_group is not None
        refs = [w.next_result.remote() for w in self.worker_group.workers]
        try:
            results = ray_tpu.get(refs, timeout=None)
        except Exception as exc:
            raise TrainingWorkerError(str(exc)) from exc
        finished = [r is None for r in results]
        if all(finished):
            return None
        if any(finished):
            raise TrainingWorkerError(
                "Workers finished unevenly — mismatched session.report calls"
            )
        return results

    def profile_records(self) -> list:
        """Per-rank profiler rings from the live worker group (empty when
        no group is up or instrumentation is off)."""
        if self.worker_group is None:
            return []
        return self.worker_group.profile_records()

    def restart(self) -> None:
        """Tear down and re-form the worker group (reference _restart :625).
        On TPU a failed host invalidates the whole mesh, so restart is always
        whole-group (SURVEY.md §7 hard part 4)."""
        self.shutdown()
        self.start()

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
