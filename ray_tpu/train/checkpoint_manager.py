"""Top-K checkpoint bookkeeping (reference: air/_internal/checkpoint_manager.py
driven by CheckpointConfig air/config.py:574)."""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig


class CheckpointManager:
    def __init__(self, config: CheckpointConfig):
        self._config = config
        self._heap: list = []  # (sort_score, counter, checkpoint, metrics)
        self._counter = itertools.count()
        self.latest: Optional[Checkpoint] = None
        self.latest_metrics: dict = {}
        # Driver-side checkpoint-phase accounting for the train profiler:
        # registration is cheap for dict checkpoints but can spill/copy for
        # directory ones, and that time belongs to the round that paid it.
        self.last_register_s: float = 0.0
        self.register_time_s: float = 0.0
        self.registrations: int = 0

    def register(self, checkpoint: Checkpoint, metrics: dict) -> None:
        t0 = time.perf_counter()
        self._register(checkpoint, metrics)
        self.last_register_s = time.perf_counter() - t0
        self.register_time_s += self.last_register_s
        self.registrations += 1

    def _register(self, checkpoint: Checkpoint, metrics: dict) -> None:
        self.latest = checkpoint
        self.latest_metrics = dict(metrics)
        attr = self._config.checkpoint_score_attribute
        if attr is not None and attr in metrics:
            score = float(metrics[attr])
        else:
            # No score attribute: recency-ordered.
            score = float(next(self._counter))
        # Min-heap keeps the WORST at the root for eviction.
        sort_score = score if self._config.checkpoint_score_order == "max" else -score
        heapq.heappush(
            self._heap, (sort_score, next(self._counter), checkpoint, dict(metrics))
        )
        keep = self._config.num_to_keep
        if keep is not None:
            while len(self._heap) > keep:
                heapq.heappop(self._heap)

    @property
    def best(self) -> Optional[Checkpoint]:
        if not self._heap:
            return self.latest
        return max(self._heap, key=lambda e: (e[0], e[1]))[2]

    @property
    def best_metrics(self) -> dict:
        if not self._heap:
            return self.latest_metrics
        return max(self._heap, key=lambda e: (e[0], e[1]))[3]

    def all_checkpoints(self) -> list[Checkpoint]:
        return [e[2] for e in sorted(self._heap, key=lambda e: e[1])]
