"""DataParallelTrainer — the Trainer surface.

Reference: train/data_parallel_trainer.py:58,422 + train/trainer.py:41
(TrainingIterator) + base_trainer.py:559 (fit). Differences by design: the
trainer runs standalone (the reference wraps every fit in a 1-trial Tune run;
here Tune drives trainers through the same interface instead, keeping the
fit path free of tune plumbing).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig, TrainConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingWorkerError
from ray_tpu.train.checkpoint_manager import CheckpointManager


class DataParallelTrainer:
    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        train_config: Optional[TrainConfig] = None,
        datasets: Optional[dict] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._train_config = dict(train_loop_config or {})
        self._backend_config = backend_config or self._default_backend_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.train_config = train_config or TrainConfig()
        self._datasets = dict(datasets or {})
        self._resume_checkpoint = resume_from_checkpoint
        self._latest_checkpoint: Optional[Checkpoint] = None
        self._result_callbacks: list[Callable[[dict], None]] = []
        # Display name for the run registry (Tune sets it to the trial id).
        self._run_name: Optional[str] = None
        # Live executor while fit() runs — the mid-fit liveness surface.
        self._executor: Optional[BackendExecutor] = None

    def profile_records(self) -> list:
        """Per-rank profiler rings straight from the live worker group —
        mid-fit liveness (e.g. from a result callback or another thread,
        without waiting for Result.train_report). [] before fit(), after
        shutdown, or when instrumentation is off."""
        executor = self._executor
        if executor is None:
            return []
        return executor.profile_records()

    def add_result_callback(self, fn: Callable[[dict], None]) -> None:
        """Called with rank-0 metrics after every report round (Tune hook)."""
        self._result_callbacks.append(fn)

    def as_trainable(self) -> type:
        """Wrap this trainer for Tune — every fit() becomes a (potentially
        multi-worker) trial, the reference's BaseTrainer.fit-wraps-a-1-trial-
        Tune-run flow inverted (train/base_trainer.py:559). Trial configs merge
        under the `train_loop_config` key, like the reference."""
        import copy

        from ray_tpu.tune.trainable import wrap_function

        base = self

        def train_fn(config):
            from ray_tpu.air import session

            trainer = copy.copy(base)
            trainer._train_config = {
                **base._train_config,
                **(config.get("train_loop_config") or {}),
            }
            if "scaling_config" in config:
                trainer.scaling_config = config["scaling_config"]
            # Tune-side restore (failure retry / PBT exploit / experiment
            # resume) arrives as the trial's loaded checkpoint — seed the
            # trainer so workers resume instead of restarting from scratch.
            restored = session.get_checkpoint()
            if restored is not None:
                trainer._resume_checkpoint = restored
            trainer._result_callbacks = list(base._result_callbacks)
            # Trial rounds reuse the train run records: name the run after
            # the trial so the registry/dashboard map trial -> telemetry.
            ctx = session.get_context()
            trainer._run_name = ctx.trial_name or ctx.trial_id or None
            # Forward each result round — with the workers' latest checkpoint,
            # so Tune-side save()/restore() (PBT, retries) is meaningful.
            trainer.add_result_callback(
                lambda m: session.report(m, checkpoint=trainer._latest_checkpoint)
            )
            result = trainer.fit()
            if result.error:
                raise result.error

        train_fn.__name__ = type(base).__name__
        return wrap_function(train_fn)

    # -- dataset sharding ----------------------------------------------------

    def _dataset_shard_fn(self, rank: int, world_size: int) -> Optional[dict]:
        if not self._datasets:
            return None
        # streaming_split iterators share ONE coordinator: build the split
        # once per (dataset, world_size) and hand rank-th iterators out.
        # Per-rank splits would each spawn a coordinator whose other n-1
        # queues nobody drains — the feeder blocks and training hangs.
        cache = getattr(self, "_split_cache", None)
        if cache is None:
            cache = self._split_cache = {}
        shards = {}
        for name, ds in self._datasets.items():
            split = getattr(ds, "streaming_split", None)
            if split is not None:
                key = (name, world_size)
                if key not in cache:
                    cache[key] = ds.streaming_split(world_size)
                shards[name] = cache[key][rank]
            elif isinstance(ds, (list, tuple)):
                shards[name] = ds[rank::world_size]
            else:
                shards[name] = ds
        return shards

    # -- fit -----------------------------------------------------------------

    def fit(self) -> Result:
        failure_config = self.run_config.failure_config or FailureConfig()
        max_failures = failure_config.max_failures
        ckpt_manager = CheckpointManager(self.run_config.checkpoint_config)
        executor = BackendExecutor(self._backend_config, self.scaling_config)
        self._executor = executor
        history: list[dict] = []
        error: Optional[BaseException] = None
        failures = 0
        start = time.monotonic()
        run = None
        if self.train_config.instrument:
            from ray_tpu.train.observability import TrainRunRecord, register_run

            run = register_run(
                TrainRunRecord(
                    name=self._run_name or self.run_config.name or type(self).__name__,
                    trainer=type(self).__name__,
                    num_workers=self.scaling_config.num_workers,
                    straggler_factor=self.train_config.straggler_factor,
                    straggler_min_s=self.train_config.straggler_min_s,
                    rounds_capacity=self.train_config.rounds_capacity,
                )
            )

        try:
            while True:
                try:
                    # Whole-group (re-)form — restart() is shutdown+start,
                    # idempotent when nothing is up yet (TPU mesh restarts
                    # are all-or-nothing). Inside the try so a death DURING
                    # the re-form (e.g. placement raced node-failure
                    # detection) counts as another recoverable failure.
                    executor.restart()
                    # Fresh split coordinators per attempt: after a worker
                    # failure the old iterators are mid-stream/exhausted.
                    self._split_cache = {}
                    self._run_training(executor, ckpt_manager, history, run)
                    break
                except TrainingWorkerError as exc:
                    failures += 1
                    if max_failures != -1 and failures > max_failures:
                        error = exc
                        break
                    # Resume the next attempt from the latest checkpoint.
                    self._resume_checkpoint = ckpt_manager.latest or self._resume_checkpoint
        except BaseException as exc:
            # Anything outside the worker-retry path (group-form timeout,
            # KeyboardInterrupt, ...) propagates — but the run record must
            # not report a crashed fit as ok.
            error = exc
            raise
        finally:
            executor.shutdown()
            if run is not None:
                # Closes the `train.fit` root span every round span chains
                # to — one fit(), one connected trace.
                run.finish(error)

        metrics = dict(ckpt_manager.latest_metrics or (history[-1] if history else {}))
        metrics.setdefault("time_total_s", time.monotonic() - start)
        metrics["training_iteration"] = len(history)
        return Result(
            metrics=metrics,
            checkpoint=ckpt_manager.best,
            error=error,
            path=self.run_config.resolved_storage_path(),
            metrics_history=history,
            train_report=run.report() if run is not None else None,
        )

    def _run_training(
        self,
        executor: BackendExecutor,
        ckpt_manager: CheckpointManager,
        history: list[dict],
        run=None,
    ) -> None:
        observability = None
        if run is not None:
            observability = {
                "trace": (run.trace_id, run.fit_span_id),
                # Continue the driver's round numbering across failure
                # restarts so retried rounds reuse their span ids (a retry
                # is the same logical round re-executed).
                "round_offset": len(history),
                "capacity": self.train_config.profiler_capacity,
            }
        executor.start_training(
            self._train_fn,
            self._train_config,
            self._resume_checkpoint,
            self._dataset_shard_fn,
            observability=observability,
        )
        while True:
            round_start = time.time()
            results = executor.next_results()
            if results is None:
                return
            profiles = [r.pop("profile", None) for r in results]
            rank0 = results[0]
            metrics = rank0["metrics"]
            # Rank 0's checkpoint is authoritative (reference: master-rank
            # persistence, train/_internal/checkpoint.py:35).
            checkpoint = rank0.get("checkpoint")
            if checkpoint is not None:
                ckpt_manager.register(checkpoint, metrics)
                self._latest_checkpoint = checkpoint
            else:
                ckpt_manager.latest_metrics = dict(metrics)
            round_idx = len(history)
            history.append(dict(metrics))
            if run is not None:
                run.record_round(
                    round_idx,
                    profiles,
                    round_start,
                    time.time(),
                    checkpoint_s=(
                        ckpt_manager.last_register_s if checkpoint is not None else 0.0
                    ),
                )
            for callback in self._result_callbacks:
                callback(dict(metrics))
