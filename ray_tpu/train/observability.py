"""Training-path observability: per-worker step profiler + run telemetry.

The training mirror of ``llm/observability.py`` (PR 4's serving plane),
gated by ``TrainConfig.instrument`` the way the engine plane is gated by
``EngineConfig.instrument``:

  * ``StepProfiler`` — one per ``RayTrainWorker`` runner thread. A report
    *round* runs from just before one ``session.report`` rendezvous put to
    just before the next; within it, wall time is attributed to phases:

      - ``report``     time blocked in the rendezvous (driver consumption);
                       always at the start of the round it is recorded in
      - ``data_wait``  dataset-iterator ``next()`` waits + ``prepare_batch``
      - ``compute``    ``prepare_step``-wrapped jitted steps
                       (block_until_ready-bounded, so async dispatch cannot
                       hide device time)
      - ``collective`` host collectives (``util.collective`` allreduce/
                       broadcast/barrier/...)
      - ``checkpoint`` ``Checkpoint.from_dict`` / ``save_sharded`` /
                       ``save_train_state``

    Rounds land in a bounded per-worker ring (``RayTrainWorker.
    profile_records`` → ``WorkerGroup.profile_records``) AND ride each
    report to the driver, so the trainer aggregates without extra RPCs.
    Every phase clock doubles as a fault-injection site
    (``train.<phase>``, detail ``rank=<r>``) so chaos tests can delay one
    rank's phase deterministically.

  * ``TrainRunRecord`` — driver-side, one per ``fit()``. Per round it
    computes per-phase min/median/max across ranks, flags *stragglers*
    (rank whose non-report work time exceeds the low-median across ranks
    by ``TrainConfig.straggler_factor``, with its dominant phase), observes
    the ``train_*`` histograms, and emits the connected trace:
    ``train.fit`` root → ``train.round`` per rendezvous → per-rank
    ``train.worker.round`` with per-phase children, stitched across actor
    boundaries by deterministic round span ids (``round_span_id``) via the
    ``tracing.emit_span`` explicit-context API.

Finished runs stay in a bounded process-local registry surfaced by the
dashboard ``/api/train`` panel and the ``ray-tpu train-stats`` CLI.
"""

from __future__ import annotations

import contextlib
import statistics
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from ray_tpu._private.fault_injection import maybe_fail
from ray_tpu.util import tracing

TRAIN_PHASES = ("data_wait", "compute", "collective", "checkpoint", "report")

# One report round: from sub-ms (tight CPU loops in tests) to minutes
# (real epochs with checkpointing) — the serving decade ladder extended up.
ROUND_SECONDS_BOUNDARIES = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
]
SAMPLES_PER_SECOND_BOUNDARIES = [
    1.0, 10.0, 100.0, 1e3, 1e4, 1e5, 1e6, 1e7,
]


def _train_metrics():
    """The train metric family, fetched lazily at write time so a
    ``reset_registry()`` between tests re-registers fresh instances on the
    next round (same contract as the engine metrics)."""
    from ray_tpu.util.metrics import Counter, Histogram, get_or_create

    h_round = get_or_create(
        Histogram,
        "train_round_seconds",
        "Per-rank wall time attributed to one phase of one report round",
        boundaries=ROUND_SECONDS_BOUNDARIES,
        tag_keys=("phase",),
    )
    h_report = get_or_create(
        Histogram,
        "train_report_round_seconds",
        "Driver-observed wall time of one whole report round (rendezvous "
        "across all ranks + checkpoint registration)",
        boundaries=ROUND_SECONDS_BOUNDARIES,
    )
    h_sps = get_or_create(
        Histogram,
        "train_samples_per_second",
        "Training throughput per round, summed across ranks",
        boundaries=SAMPLES_PER_SECOND_BOUNDARIES,
    )
    c_straggler = get_or_create(
        Counter,
        "train_straggler_rounds",
        "Rank-rounds flagged as stragglers, by dominant phase",
        tag_keys=("phase",),
    )
    return h_round, h_report, h_sps, c_straggler


def round_span_id(fit_span_id: str, round_idx: int) -> str:
    """Deterministic span id for round N of a fit: the driver (emitting
    ``train.round``) and every worker (parenting ``train.worker.round``)
    derive the same id with no coordination, which is what connects the
    trace across the actor boundary."""
    return f"{fit_span_id[:10]}{round_idx & 0xFFFFFF:06x}"


def current_profiler() -> Optional["StepProfiler"]:
    """The active worker's profiler, or None outside an instrumented
    training session (driver code, tune trial runners, plain tasks) —
    every hook in the hot path is one attribute read + None check."""
    from ray_tpu.air.session import _get_session

    session = _get_session()
    if session is None:
        return None
    return getattr(session, "profiler", None)


def phase_or_null(name: str):
    """``profiler.phase(name)`` when inside an instrumented training
    session, else a no-op context — the shared guard for every profiler
    hook site (collectives, checkpoint constructors, sharded save/restore),
    so the hooked body is written exactly once."""
    profiler = current_profiler()
    if profiler is None:
        return contextlib.nullcontext()
    return profiler.phase(name)


def batch_rows(batch: Any) -> int:
    """Best-effort sample count of one batch (leading dimension)."""
    try:
        if isinstance(batch, dict):
            if not batch:
                return 0
            return len(next(iter(batch.values())))
        return len(batch)
    except Exception:
        return 0


class StepProfiler:
    """Per-worker phase clock + bounded round recorder.

    Single-writer (the train runner thread); ``records`` is a deque so the
    actor's ``profile_records`` snapshot from another thread is safe.
    """

    def __init__(
        self,
        rank: int,
        world_size: int,
        trace: Optional[tuple] = None,
        round_offset: int = 0,
        capacity: int = 512,
    ):
        self.rank = rank
        self.world_size = world_size
        self.trace = tuple(trace) if trace else None  # (trace_id, fit_span_id)
        self.records: deque = deque(maxlen=capacity)
        self._detail = f"rank={rank}"
        self._round = round_offset
        self._round_start = time.perf_counter()
        self._phases: Dict[str, float] = {p: 0.0 for p in TRAIN_PHASES}
        self._samples = 0
        self._data_sources: list = []

    @contextlib.contextmanager
    def phase(self, name: str):
        """Attribute the body's wall time to `name`. The fault-injection
        site fires inside the clock, so an injected delay lands in the
        phase it targets (the straggler-test hook)."""
        t0 = time.perf_counter()
        try:
            maybe_fail(f"train.{name}", self._detail)
            yield
        finally:
            self._phases[name] += time.perf_counter() - t0

    def add(self, name: str, seconds: float) -> None:
        self._phases[name] += seconds

    def add_samples(self, n: int) -> None:
        self._samples += n

    def has_data_sources(self) -> bool:
        return bool(self._data_sources)

    def note_data_source(self, dataset: Any) -> None:
        """Remember the Dataset feeding this worker so ``data_wait`` can be
        blamed on its slowest operator (``executor.dominant_stage``)."""
        if dataset is not None and all(d is not dataset for d in self._data_sources):
            self._data_sources.append(dataset)

    def _data_blame(self) -> Optional[str]:
        try:
            from ray_tpu.data._internal.executor import dominant_stage
        except Exception:
            return None
        best: Optional[tuple] = None
        for ds in self._data_sources:
            stats = getattr(ds, "_stats", None)
            if not stats:
                continue
            stage = dominant_stage(stats)
            if stage is not None and (best is None or stage[1] > best[1]):
                best = stage
        return best[0] if best else None

    def end_round(self) -> dict:
        """Close the current round (called by ``session.report`` just
        before the rendezvous put), record it, emit its worker spans, and
        return the record so it can ride the report to the driver."""
        now_p = time.perf_counter()
        now_ts = time.time()
        duration = now_p - self._round_start
        phases = {p: round(v, 6) for p, v in self._phases.items()}
        record = {
            "round": self._round,
            "rank": self.rank,
            "duration_s": round(duration, 6),
            "phases": phases,
            "samples": self._samples,
            "data_blame": self._data_blame() if phases["data_wait"] else None,
            "time": now_ts,
        }
        self.records.append(record)
        if self.trace is not None:
            self._emit_round_spans(record, now_ts - duration, now_ts)
        self._round += 1
        self._round_start = now_p
        self._phases = {p: 0.0 for p in TRAIN_PHASES}
        self._samples = 0
        return record

    def _emit_round_spans(self, record: dict, start_ts: float, end_ts: float) -> None:
        trace_id, fit_span_id = self.trace
        worker_span_id = tracing.new_span_id()
        tracing.emit_span(
            "train.worker.round",
            start_ts,
            end_ts,
            trace_id=trace_id,
            parent_span_id=round_span_id(fit_span_id, record["round"]),
            span_id=worker_span_id,
            attributes={
                "rank": self.rank,
                "round": record["round"],
                "samples": record["samples"],
                "data_blame": record["data_blame"],
                **{f"{p}_s": v for p, v in record["phases"].items()},
            },
        )
        # Per-phase children, laid out sequentially in execution order
        # (report blocks at the round's start). Phase time is accumulated,
        # not contiguous, so the layout is synthetic — durations are exact.
        cursor = start_ts
        for phase in ("report", "data_wait", "compute", "collective", "checkpoint"):
            seconds = record["phases"][phase]
            if seconds <= 1e-6:
                continue
            tracing.emit_span(
                f"train.worker.{phase}",
                cursor,
                cursor + seconds,
                trace_id=trace_id,
                parent_span_id=worker_span_id,
            )
            cursor += seconds


class ProfiledDataIterator:
    """Wraps a ``DataIterator`` so the time the train loop *waits* for a
    batch — not the pipeline's background execution — counts as
    ``data_wait``, and batches are counted for samples/sec."""

    def __init__(self, inner: Any, profiler: StepProfiler):
        self._inner = inner
        self._prof = profiler
        profiler.note_data_source(getattr(inner, "_owner", None))

    def _timed(self, stream) -> Any:
        prof = self._prof
        it = iter(stream)
        while True:
            with prof.phase("data_wait"):
                try:
                    item = next(it)
                except StopIteration:
                    return
            prof.add_samples(batch_rows(item))
            yield item

    def iter_batches(self, **kwargs):
        return self._timed(self._inner.iter_batches(**kwargs))

    def iter_device_batches(self, **kwargs):
        return self._timed(self._inner.iter_device_batches(**kwargs))

    def iter_rows(self):
        return self._timed(self._inner.iter_rows())

    def __iter__(self):
        return self._timed(iter(self._inner))

    def __getattr__(self, name):
        return getattr(self._inner, name)


# ---------------------------------------------------------------------------
# Driver side: per-fit aggregation, straggler detection, run registry
# ---------------------------------------------------------------------------


class TrainRunRecord:
    """One ``fit()``'s telemetry: bounded round records, cumulative phase
    stats, straggler events. Written by the driver's fit loop; snapshotted
    by the dashboard/CLI from other threads (bounded deques, no locks on
    the write path)."""

    def __init__(
        self,
        name: str,
        trainer: str,
        num_workers: int,
        straggler_factor: float = 2.0,
        straggler_min_s: float = 0.05,
        rounds_capacity: int = 256,
    ):
        self.run_id = uuid.uuid4().hex[:12]
        self.name = name
        self.trainer = trainer
        self.num_workers = num_workers
        self.straggler_factor = straggler_factor
        self.straggler_min_s = straggler_min_s
        self.trace_id = tracing.new_span_id()
        self.fit_span_id = tracing.new_span_id()
        self.started = time.time()
        self.finished: Optional[float] = None
        self.error: Optional[str] = None
        self.rounds: deque = deque(maxlen=rounds_capacity)
        self.rounds_total = 0
        self.straggler_rounds = 0
        self.stragglers: deque = deque(maxlen=64)
        self.samples_total = 0
        self._phase_values: Dict[str, deque] = {
            p: deque(maxlen=2048) for p in TRAIN_PHASES
        }
        # Fetched once per run, not per round: get_or_create takes the
        # registry lock, and instances survive reset_registry() anyway
        # (they re-register lazily on their next write). Pre-merged tag
        # dicts keep the per-round loop allocation-free.
        self._metrics = _train_metrics()
        self._phase_tags = {p: {"phase": p} for p in TRAIN_PHASES}

    # -- per-round ----------------------------------------------------------

    def record_round(
        self,
        round_idx: int,
        profiles: List[Optional[dict]],
        start_ts: float,
        end_ts: float,
        checkpoint_s: float = 0.0,
    ) -> dict:
        """Fold one rendezvous round's per-rank records in: histograms,
        min/median/max per phase across ranks, straggler flags, and the
        ``train.round`` span the workers' round spans hang under."""
        h_round, h_report, h_sps, c_straggler = self._metrics
        profiles = [p for p in profiles if p]
        round_wall = max(end_ts - start_ts, 1e-9)
        for record in profiles:
            for phase in TRAIN_PHASES:
                value = record["phases"].get(phase, 0.0)
                h_round.observe(value, self._phase_tags[phase])
                self._phase_values[phase].append(value)
        h_report.observe(round_wall)
        samples = sum(r.get("samples", 0) for r in profiles)
        self.samples_total += samples
        if samples:
            h_sps.observe(samples / round_wall)

        stragglers = self._detect_stragglers(round_idx, profiles)
        for s in stragglers:
            c_straggler.inc(1.0, {"phase": s["phase"]})

        row = {
            "round": round_idx,
            "duration_s": round(round_wall, 6),
            "checkpoint_s": round(checkpoint_s, 6),
            "samples": samples,
            "phase_stats": _phase_stats(profiles),
            "stragglers": stragglers,
            "ranks": profiles,
            "time": end_ts,
        }
        self.rounds.append(row)
        self.rounds_total += 1
        if stragglers:
            self.straggler_rounds += 1

        tracing.emit_span(
            "train.round",
            start_ts,
            end_ts,
            trace_id=self.trace_id,
            parent_span_id=self.fit_span_id,
            span_id=round_span_id(self.fit_span_id, round_idx),
            attributes={
                "round": round_idx,
                "ranks": len(profiles),
                "samples": samples,
                "checkpoint_s": round(checkpoint_s, 6),
                "stragglers": [s["rank"] for s in stragglers],
            },
        )
        return row

    def _detect_stragglers(
        self, round_idx: int, profiles: List[dict]
    ) -> List[dict]:
        """A straggler's *work* time (round minus rendezvous wait) exceeds
        the low-median across ranks by ``straggler_factor``. Total round
        times are useless here: the rendezvous equalizes them — fast ranks
        just block longer in ``report`` — so the report phase is excluded
        from both the comparison and the dominant-phase blame."""
        if len(profiles) < 2:
            return []
        works = {
            r["rank"]: max(r["duration_s"] - r["phases"].get("report", 0.0), 0.0)
            for r in profiles
        }
        # median_low: with few ranks (the common 2-4 worker case) the
        # interpolated median is dragged halfway toward the straggler
        # itself, which can mask it exactly at the threshold.
        median = statistics.median_low(list(works.values()))
        out = []
        for record in profiles:
            work = works[record["rank"]]
            if work <= self.straggler_factor * median:
                continue
            if work - median < self.straggler_min_s:
                continue
            phases = {
                p: v for p, v in record["phases"].items() if p != "report"
            }
            # Blame the largest phase clock — unless the clocks don't cover
            # the excess work (unhooked user code), in which case naming a
            # near-zero phase would send the operator chasing the wrong
            # subsystem: call it what it is.
            tracked = sum(phases.values())
            if phases and tracked >= 0.5 * work:
                dominant = max(phases, key=phases.get)
            else:
                dominant = "untracked"
            out.append(
                {
                    "round": round_idx,
                    "rank": record["rank"],
                    "work_s": round(work, 6),
                    "median_work_s": round(median, 6),
                    "phase": dominant,
                    "data_blame": record.get("data_blame"),
                }
            )
        self.stragglers.extend(out)
        return out

    # -- lifecycle ----------------------------------------------------------

    def finish(self, error: Optional[BaseException] = None) -> None:
        self.finished = time.time()
        self.error = repr(error) if error is not None else None
        tracing.emit_span(
            "train.fit",
            self.started,
            self.finished,
            trace_id=self.trace_id,
            parent_span_id=None,
            span_id=self.fit_span_id,
            attributes={
                "run_id": self.run_id,
                "name": self.name,
                "trainer": self.trainer,
                "num_workers": self.num_workers,
                "rounds": self.rounds_total,
                "straggler_rounds": self.straggler_rounds,
                "status": "error" if error is not None else "ok",
                **({"error": self.error} if error is not None else {}),
            },
        )

    def report(self, rounds_limit: int = 32) -> dict:
        """Aggregate snapshot: what ``Result.train_report``, the dashboard
        panel, and the CLI all serve."""
        rounds = list(self.rounds)
        if rounds_limit >= 0:
            rounds = rounds[len(rounds) - rounds_limit:] if rounds_limit else []
        return {
            "run_id": self.run_id,
            "name": self.name,
            "trainer": self.trainer,
            "num_workers": self.num_workers,
            "trace_id": self.trace_id,
            "started": self.started,
            "finished": self.finished,
            "error": self.error,
            "rounds_total": self.rounds_total,
            "samples_total": self.samples_total,
            "straggler_rounds": self.straggler_rounds,
            "stragglers": list(self.stragglers),
            "phase_stats": {
                p: _min_median_max(list(vs))
                for p, vs in self._phase_values.items()
                if vs
            },
            "rounds": rounds,
        }


def _min_median_max(values: List[float]) -> dict:
    """One sort, three reads (statistics.median re-sorts and type-checks;
    this runs 5x per round on the driver's hot path)."""
    ordered = sorted(values)
    n = len(ordered)
    mid = ordered[n // 2] if n % 2 else (ordered[n // 2 - 1] + ordered[n // 2]) / 2
    return {
        "min": round(ordered[0], 6),
        "median": round(mid, 6),
        "max": round(ordered[-1], 6),
    }


def _phase_stats(profiles: List[dict]) -> Dict[str, dict]:
    out = {}
    for phase in TRAIN_PHASES:
        values = [r["phases"].get(phase, 0.0) for r in profiles]
        if values:
            out[phase] = _min_median_max(values)
    return out


_RUNS_LOCK = threading.Lock()
_RUNS: "OrderedDict[str, TrainRunRecord]" = OrderedDict()
_RUNS_CAPACITY = 32


def register_run(record: TrainRunRecord) -> TrainRunRecord:
    with _RUNS_LOCK:
        _RUNS[record.run_id] = record
        while len(_RUNS) > _RUNS_CAPACITY:
            _RUNS.popitem(last=False)
    return record


def get_run(run_id: str) -> Optional[TrainRunRecord]:
    with _RUNS_LOCK:
        return _RUNS.get(run_id)


def list_runs(limit: int = 16, rounds_limit: int = 8) -> List[dict]:
    """Newest-first snapshots of recent training runs (in this process —
    the driver and the in-process head share it)."""
    with _RUNS_LOCK:
        records = list(_RUNS.values())
    return [r.report(rounds_limit=rounds_limit) for r in records[::-1][:limit]]


def reset_runs() -> None:
    """Test isolation."""
    with _RUNS_LOCK:
        _RUNS.clear()
