"""Sharded array checkpointing via orbax — the TPU checkpoint format.

SURVEY.md §5 checkpoint/resume: "replace torch state_dicts with orbax-style
sharded array checkpoints saved per-host". The reference persists rank-0
torch state_dicts (train/_internal/checkpoint.py); on TPU a model can exceed
one host's RAM, so params stay device-resident and each host writes only its
shards: orbax handles the OCDBT layout, coordination and atomic finalization.
Restore takes an abstract target (shapes + shardings) so arrays land directly
on the right devices — no host-memory staging of the full tree.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def _checkpoint_phase():
    """Train-profiler hook: inside an instrumented training session, time
    spent writing/reading sharded checkpoints is the round's `checkpoint`
    phase; everywhere else this is a no-op."""
    from ray_tpu.train.observability import phase_or_null

    return phase_or_null("checkpoint")


def save_sharded(path: str, state: Any, *, force: bool = True) -> str:
    """Write a pytree of (possibly sharded, device-resident) arrays."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with _checkpoint_phase():
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state, force=force)
        ckptr.wait_until_finished()
    return path


def restore_sharded(
    path: str,
    target: Optional[Any] = None,
    *,
    mesh=None,
    shardings: Optional[Any] = None,
) -> Any:
    """Restore a pytree saved by save_sharded.

    target: a pytree of arrays or jax.ShapeDtypeStruct matching the saved
    structure; when `shardings` (a matching pytree of NamedShardings) is
    given, restored arrays are placed shard-by-shard onto those devices.
    With no target, the tree restores fully replicated on host.
    """
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if target is None:
        with _checkpoint_phase():
            return ckptr.restore(path)
    def _abstract(x):
        if not hasattr(x, "shape"):  # python scalars in optimizer state
            import jax.numpy as jnp

            x = jnp.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    abstract = jax.tree_util.tree_map(_abstract, target)
    if shardings is not None:
        abstract = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract,
            shardings,
        )
    with _checkpoint_phase():
        return ckptr.restore(path, abstract)


def save_train_state(
    path: str, params: Any, opt_state: Any = None, step: int = 0
) -> str:
    """Convenience: one directory holding params (+ optimizer state + step),
    the JaxTrainer's native checkpoint format."""
    state = {"params": params, "step": step}
    if opt_state is not None:
        state["opt_state"] = opt_state
    return save_sharded(path, state)


def restore_train_state(
    path: str, params_target: Any = None, opt_state_target: Any = None
) -> dict:
    target = None
    if params_target is not None:
        target = {"params": params_target, "step": 0}
        if opt_state_target is not None:
            target["opt_state"] = opt_state_target
    return restore_sharded(path, target)
