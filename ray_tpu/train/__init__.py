from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
    TrainConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.air.session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_mesh,
    get_world_rank,
    get_world_size,
    report,
)
from ray_tpu.train.backend import Backend, BackendConfig, JaxBackend, JaxBackendConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingWorkerError
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.sharded_checkpoint import (
    restore_sharded,
    restore_train_state,
    save_sharded,
    save_train_state,
)
from ray_tpu.train.jax_trainer import (
    JaxTrainer,
    prepare_batch,
    prepare_params,
    prepare_step,
)
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.train.observability import (
    StepProfiler,
    TrainRunRecord,
    list_runs,
)

__all__ = [
    "Backend",
    "BackendConfig",
    "BackendExecutor",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxBackend",
    "JaxBackendConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "StepProfiler",
    "TrainConfig",
    "TrainRunRecord",
    "TrainingWorkerError",
    "WorkerGroup",
    "list_runs",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "get_mesh",
    "get_world_rank",
    "get_world_size",
    "prepare_batch",
    "prepare_params",
    "prepare_step",
    "report",
    "restore_sharded",
    "restore_train_state",
    "save_sharded",
    "save_train_state",
]
