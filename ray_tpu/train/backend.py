"""Training backends — per-framework worker-group setup.

Reference: the Backend plugin protocol (train/_internal/backend_executor.py
drives Backend.on_start/on_shutdown; torch impl at train/torch/config.py:155).
The TPU re-design replaces "start a torch.distributed process group over NCCL"
with "form the device mesh + host collective group" (SURVEY.md §2.5: mesh
formation IS the framework's job; gradient collectives are XLA's).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks called by the BackendExecutor around the worker group."""

    def on_start(self, worker_group, backend_config: "BackendConfig") -> None:
        pass

    def on_training_start(self, worker_group, backend_config: "BackendConfig") -> None:
        pass

    def on_shutdown(self, worker_group, backend_config: "BackendConfig") -> None:
        pass


# ---------------------------------------------------------------------------
# JAX backend
# ---------------------------------------------------------------------------


@dataclass
class JaxBackendConfig(BackendConfig):
    """Mesh-forming backend config.

    mesh_strategy/axes: how to arrange this trainer's chips
    (ray_tpu.parallel.auto_mesh strategies, or explicit MeshSpec).
    coordinator_port: jax.distributed rendezvous port for real multi-host pods.
    """

    mesh_spec: Optional[Any] = None  # parallel.MeshSpec
    mesh_strategy: str = "dp"
    collective_group: str = "train"
    multihost: bool = False
    coordinator_port: int = 8476

    @property
    def backend_cls(self):
        return JaxBackend


def _form_mesh(context, config: JaxBackendConfig, num_workers: int):
    """Runs ON each worker: initialize distributed jax (multi-host), build the
    mesh over the worker's visible devices, and join the host collective group.

    Single-controller-per-host model (SURVEY.md CS4): world_size == number of
    hosts; each worker drives all chips jax exposes to its process. In the
    in-process test runtime all workers share one jax client, so the mesh spans
    the same devices in every worker — exactly what a real pod's global SPMD
    mesh looks like from each host.
    """
    import jax

    from ray_tpu.parallel import MeshSpec, auto_mesh
    from ray_tpu.util import collective

    if config.multihost and num_workers > 1:
        from ray_tpu.parallel.mesh import initialize_multi_host

        # Rank 0's host address is published via the named collective actor in
        # a real deployment; in-process this is a no-op path.
        initialize_multi_host(
            coordinator_address=f"localhost:{config.coordinator_port}",
            num_processes=num_workers,
            process_id=context.world_rank,
        )
    # Membership is stashed on the worker context: the train loop runs on a
    # different thread (the runner), which resolves groups via its session.
    state = collective.create_group_state(
        world_size=num_workers,
        rank=context.world_rank,
        group_name=config.collective_group,
    )
    context.extras.setdefault("collective_groups", {})[config.collective_group] = state
    devices = jax.devices()
    spec = config.mesh_spec or auto_mesh(len(devices), strategy=config.mesh_strategy)
    context.devices = devices
    context.mesh = spec.build(devices)
    return len(devices)


class JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxBackendConfig) -> None:
        worker_group.execute(
            _form_mesh, backend_config, worker_group.num_workers
        )

    def on_shutdown(self, worker_group, backend_config: JaxBackendConfig) -> None:
        def _leave(context):
            import ray_tpu

            state = context.extras.get("collective_groups", {}).pop(
                backend_config.collective_group, None
            )
            # Rank 0 kills the rendezvous actor so the next trainer can form a
            # group of a different size under the same name.
            if state is not None and context.world_rank == 0:
                try:
                    ray_tpu.kill(state.handle)
                except Exception:
                    pass

        try:
            worker_group.execute(_leave)
        except Exception:
            pass
