"""JaxTrainer + in-loop helpers — the TPU-native Train path.

The BASELINE.json north-star surface: `JaxTrainer` is the `TorchTrainer`
equivalent whose workers drive TPU chips and whose gradient sync is XLA
(`lax.psum` over ICI) instead of NCCL DDP. One worker per TPU host
(single-controller-per-host); the backend forms the mesh before the user loop
starts (reference flow: CS4 in SURVEY.md).

In-loop helpers (the `prepare_model`/`prepare_data_loader` analogs,
train/torch/train_loop_utils.py:245,329): `prepare_params` shards a param tree
onto the mesh, `prepare_batch` shards inputs over the data axes, `prepare_step`
jits the step with donated params.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.air.config import ScalingConfig
from ray_tpu.air import session
from ray_tpu.train.backend import JaxBackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


class JaxTrainer(DataParallelTrainer):
    _default_backend_config = JaxBackendConfig()

    def __init__(self, train_loop_per_worker: Callable, **kwargs):
        kwargs.setdefault("backend_config", JaxBackendConfig())
        super().__init__(train_loop_per_worker, **kwargs)


# -- in-loop helpers ---------------------------------------------------------


def prepare_params(params: Any, rules: Optional[dict] = None) -> Any:
    """Shard a parameter pytree onto the session mesh (FSDP heuristic when the
    tree carries no logical-axis metadata)."""
    import jax

    from ray_tpu.parallel import FSDP_RULES, infer_param_sharding

    mesh = session.get_mesh()
    shardings = infer_param_sharding(mesh, params, rules or FSDP_RULES)
    return jax.device_put(params, shardings)


def prepare_batch(batch: Any) -> Any:
    """Shard a batch pytree over the mesh's data axes. Under an
    instrumented session the host→device put counts as `data_wait` (it is
    the step's wait-for-input tail), and batches feed the samples/sec
    clock unless a profiled dataset iterator is already counting them."""
    import jax

    from ray_tpu.parallel import batch_sharding
    from ray_tpu.train.observability import batch_rows, current_profiler

    mesh = session.get_mesh()
    sharding = batch_sharding(mesh)
    profiler = current_profiler()
    if profiler is None:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), batch
        )
    with profiler.phase("data_wait"):
        out = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), batch
        )
    if not profiler.has_data_sources():
        profiler.add_samples(batch_rows(batch))
    return out


def prepare_step(step_fn: Callable, donate_argnums=(0,)) -> Callable:
    """jit the train step; shardings propagate from the (already-sharded)
    inputs, XLA inserts the gradient collectives. Under an instrumented
    session each call is timed into the `compute` phase and bounded by
    block_until_ready — otherwise async dispatch would bill device time to
    whatever host code touches the result next."""
    import jax

    from ray_tpu.train.observability import current_profiler

    jitted = jax.jit(step_fn, donate_argnums=donate_argnums)
    # The session's profiler is fixed for the loop's lifetime, so decide
    # once at prepare time: uninstrumented (or driver-side) callers get the
    # jit callable itself — full jit API (.lower, .clear_cache), zero
    # per-call overhead.
    profiler = current_profiler()
    if profiler is None:
        return jitted

    def instrumented_step(*args, **kwargs):
        with profiler.phase("compute"):
            out = jitted(*args, **kwargs)
            jax.block_until_ready(out)
        return out

    return instrumented_step


def report_from_rank0(metrics: dict, checkpoint=None) -> None:
    """report() with identical metrics from every rank; checkpoint only from
    rank 0 (the reference persists the master rank's checkpoint)."""
    if session.get_world_rank() == 0:
        session.report(metrics, checkpoint=checkpoint)
    else:
        session.report(metrics)
