"""Paged KV cache block allocator.

CPU-side bookkeeping for the preallocated [num_blocks, block_size, H, D]
device pools owned by the model runner: a free list of block ids, per-call
alloc/free, and utilization accounting. Block 0 is never handed out — it is
the null block that pads block tables and absorbs masked-lane scatters, so
a gather through an id of 0 is always safe (and always masked).
"""

from __future__ import annotations

from typing import List

NULL_BLOCK = 0


class CacheOutOfBlocks(Exception):
    """Raised when an allocation cannot be satisfied; the scheduler turns
    this into a preemption rather than letting it escape."""


def blocks_for_tokens(num_tokens: int, block_size: int) -> int:
    return -(-num_tokens // block_size)


class BlockAllocator:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # LIFO reuse: a just-freed block is the next handed out, so a hot
        # pool touches few distinct cache pages.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated: set[int] = set()

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError("cannot allocate a negative block count")
        if n > len(self._free):
            raise CacheOutOfBlocks(
                f"requested {n} blocks, {len(self._free)} free"
            )
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(
                    f"freeing block {b} that is not allocated (double free?)"
                )
            self._allocated.remove(b)
            self._free.append(b)

    def utilization(self) -> float:
        return len(self._allocated) / self.num_usable
