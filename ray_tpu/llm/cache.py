"""Refcounted, content-addressed paged-KV block allocator.

CPU-side bookkeeping for the preallocated [num_blocks, block_size, H, D]
device pools owned by the model runner. Block 0 is never handed out — it is
the null block that pads block tables and absorbs masked-lane scatters, so
a gather through an id of 0 is always safe (and always masked).

Block ids are storage-format-agnostic: with `kv_cache_dtype="int8"` the
runner keeps int8 pools plus per-token scale tensors addressed by the SAME
block ids, and every device-side block operation (scatter, copy-on-write
`copy_block`) moves values and scales together — so sharing, refcounts,
eviction and CoW here need no notion of quantization. int8 halves the
bytes per cached token, which doubles `num_blocks` for the same HBM: more
sequences resident, fewer preemptions, better continuous batching.

Block ids are also *shard*-invariant: under tensor parallelism
(EngineConfig.tensor_parallel_size > 1) the device pools shard on the HEAD
axis — every chip holds the same [num_blocks, block_size] block grid, just
its own heads' slice of each block — so this allocator, the prefix cache,
and the scheduler stay completely host-global and shard-oblivious. The
bytes that DO change per chip are reported by `kv_pool_bytes_sharded`.

Automatic prefix caching (vLLM-style, restated for this allocator):

  * Every FULL block of a sequence gets a content key: the chain hash of
    its token ids folded with its predecessor's key, so a key identifies
    the whole prefix up to and including that block, not just its own
    tokens. Partial blocks have no key and are never shared.
  * A hash → block map serves cache hits: admission matches the longest
    chain of keys already resident and bumps refcounts instead of
    recomputing the prefix (`match_prefix` + `touch`).
  * `free()` decrements refcounts. A block that reaches refcount 0 with a
    registered key keeps its device content and parks in an *evictable*
    pool; unkeyed blocks return to the plain free list. `allocate()` serves
    the free list first and evicts evictable blocks (LRU by default, FIFO
    as a policy knob) only under pressure — so a preempted or finished
    sequence's prefix stays warm until the space is actually needed.
  * Shared blocks are immutable. The one write that can target a shared
    block — re-prefilling a prompt that is cached in full, where the last
    token's K/V lands inside the last shared block — is copy-on-write: the
    scheduler allocates a private copy and the engine device-copies the
    block before writing (see Scheduler._admit).
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

NULL_BLOCK = 0

EVICTION_LRU = "lru"
EVICTION_FIFO = "fifo"
EVICTION_POLICIES = (EVICTION_LRU, EVICTION_FIFO)


class CacheOutOfBlocks(Exception):
    """Raised when an allocation cannot be satisfied; the scheduler turns
    this into a preemption rather than letting it escape."""


def blocks_for_tokens(num_tokens: int, block_size: int) -> int:
    return -(-num_tokens // block_size)


def kv_pool_bytes_sharded(
    num_layers: int,
    num_blocks: int,
    block_size: int,
    num_heads: int,
    head_dim: int,
    value_itemsize: int,
    scale_itemsize: Optional[int] = None,
    tensor_parallel_size: int = 1,
) -> Dict[str, int]:
    """Byte accounting for BOTH KV pools (K + V values, plus their scale
    tensors when quantized) under head-axis tensor parallelism.

    The pools are [L, N, bs, H, D] (scales [L, N, bs, H]) sharded on H, so
    each chip holds exactly aggregate / tp bytes — the number that decides
    whether a model's cache fits per-chip HBM, which is what
    `tensor_parallel_size` exists to change. Pure-int host math (this
    module is imported by jax-free paths): callers pass itemsizes, e.g.
    `np.dtype(runner.kv_cache_dtype).itemsize`.
    """
    if tensor_parallel_size < 1:
        raise ValueError("tensor_parallel_size must be >= 1")
    if num_heads % tensor_parallel_size:
        raise ValueError(
            f"num_heads {num_heads} not divisible by tensor_parallel_size "
            f"{tensor_parallel_size} (the pools shard on the head axis)"
        )
    slots = num_layers * num_blocks * block_size * num_heads
    per_pool = slots * head_dim * value_itemsize
    if scale_itemsize is not None:
        per_pool += slots * scale_itemsize
    aggregate = 2 * per_pool  # K and V
    return {
        "aggregate": aggregate,
        "per_shard": aggregate // tensor_parallel_size,
        "tensor_parallel_size": tensor_parallel_size,
    }


def hash_block_tokens(
    prev_hash: Optional[int], token_ids: Sequence[int]
) -> int:
    """Chain key for one full block: folds the predecessor block's key, so
    equal keys mean equal *prefixes*, not merely equal block contents."""
    return hash((prev_hash, tuple(token_ids)))


def prefix_block_hashes(
    token_ids: Sequence[int], block_size: int
) -> List[int]:
    """Chain keys for every full block of `token_ids` (a trailing partial
    block has no key — partial blocks are never shared)."""
    out: List[int] = []
    prev: Optional[int] = None
    for start in range(
        0, (len(token_ids) // block_size) * block_size, block_size
    ):
        prev = hash_block_tokens(prev, token_ids[start : start + block_size])
        out.append(prev)
    return out


class BlockAllocator:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        eviction_policy: str = EVICTION_LRU,
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        if eviction_policy not in EVICTION_POLICIES:
            raise ValueError(
                f"eviction_policy must be one of {EVICTION_POLICIES}, "
                f"got {eviction_policy!r}"
            )
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self.eviction_policy = eviction_policy
        # LIFO reuse: a just-freed block is the next handed out, so a hot
        # pool touches few distinct cache pages.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated: set[int] = set()  # ids with refcount >= 1
        self._refs: Dict[int, int] = {}
        # Prefix cache state. _hash_to_block holds the canonical block per
        # chain key (content valid whether the block is referenced or
        # evictable); _evictable maps refcount-0 keyed blocks to their
        # eviction priority (lower evicts first).
        self._hash_to_block: Dict[int, int] = {}
        self._block_hash: Dict[int, int] = {}
        self._evictable: Dict[int, int] = {}
        self._fifo_order: Dict[int, int] = {}
        self._tick = itertools.count()
        self.num_evictions = 0
        # Spill hook: invoked with (block, chain_hash) just before a keyed
        # block's device content is discarded by eviction, while the
        # content is still valid on device — the KV fabric demotes the
        # block to its host-DRAM tier here. The allocator stays jax-free:
        # whoever sets the hook owns the device read. A raising hook is
        # contained so allocator bookkeeping can never be left torn.
        self.on_evict: Optional[Callable[[int, int], None]] = None

    # ---------------- accounting ----------------

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        """Blocks an allocation can claim: unused + evictable."""
        return len(self._free) + len(self._evictable)

    @property
    def num_evictable(self) -> int:
        return len(self._evictable)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def utilization(self) -> float:
        return len(self._allocated) / self.num_usable

    # ---------------- alloc / free ----------------

    def can_allocate(self, n: int) -> bool:
        return n <= self.num_free

    def allocate(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError("cannot allocate a negative block count")
        if n > self.num_free:
            raise CacheOutOfBlocks(
                f"requested {n} blocks, {self.num_free} free "
                f"({len(self._free)} unused + {len(self._evictable)} "
                "evictable)"
            )
        out = []
        for _ in range(n):
            b = self._free.pop() if self._free else self._evict_one()
            self._refs[b] = 1
            self._allocated.add(b)
            out.append(b)
        return out

    def _evict_one(self) -> int:
        b = min(self._evictable, key=self._evictable.__getitem__)
        del self._evictable[b]
        h = self._block_hash.pop(b, None)
        if h is not None and self._hash_to_block.get(h) == b:
            del self._hash_to_block[h]
            if self.on_evict is not None:
                try:
                    self.on_evict(b, h)
                except Exception:
                    pass  # spill is best-effort; eviction must complete
        self._fifo_order.pop(b, None)
        self.num_evictions += 1
        return b

    def evictable_items(self) -> List[Tuple[int, int]]:
        """(block, chain_hash) for every keyed refcount-0 block whose
        device content is still valid — the set a draining engine flushes
        into the KV fabric before its pool dies with the actor."""
        return [
            (b, self._block_hash[b])
            for b in self._evictable
            if b in self._block_hash
        ]

    def free(self, blocks: List[int]) -> None:
        # Validate the whole call before mutating anything: a bad id or a
        # duplicate in one list must not leave the allocator half-updated.
        seen: set[int] = set()
        for b in blocks:
            if b in seen:
                raise ValueError(
                    f"freeing block {b} more than once in a single call"
                )
            seen.add(b)
            if self._refs.get(b, 0) < 1:
                raise ValueError(
                    f"freeing block {b} that is not allocated (double free?)"
                )
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b]:
                continue
            del self._refs[b]
            self._allocated.discard(b)
            h = self._block_hash.get(b)
            if h is not None and self._hash_to_block.get(h) == b:
                # Content stays valid on device; park it for reuse.
                if self.eviction_policy == EVICTION_FIFO:
                    pri = self._fifo_order.setdefault(b, next(self._tick))
                else:
                    pri = next(self._tick)
                self._evictable[b] = pri
            else:
                self._free.append(b)

    # ---------------- prefix cache ----------------

    def match_prefix(self, block_hashes: Sequence[int]) -> List[int]:
        """Longest chain of cached blocks for these chain keys, in prefix
        order. Returned blocks are NOT protected — `touch` them before any
        allocation can evict them."""
        out: List[int] = []
        for h in block_hashes:
            b = self._hash_to_block.get(h)
            if b is None:
                break
            out.append(b)
        return out

    def touch(self, blocks: Sequence[int]) -> None:
        """Take a reference on cached blocks (reviving evictable ones)."""
        for b in blocks:
            if self._refs.get(b, 0):
                self._refs[b] += 1
            elif b in self._evictable:
                del self._evictable[b]
                self._refs[b] = 1
                self._allocated.add(b)
            else:
                raise ValueError(
                    f"touch of block {b} that is neither allocated nor "
                    "evictable"
                )

    def register(self, block: int, block_hash: int) -> bool:
        """Publish a just-filled full block under its chain key so future
        admissions can share it. First writer wins: if the key is already
        mapped (another sequence computed the same prefix), the caller's
        block stays private and returns to the free list when freed."""
        if not self.enable_prefix_caching:
            return False
        if block == NULL_BLOCK or self._refs.get(block, 0) < 1:
            raise ValueError(
                f"register of block {block} that is not a live allocation"
            )
        if block_hash in self._hash_to_block:
            return False
        self._hash_to_block[block_hash] = block
        self._block_hash[block] = block_hash
        self._fifo_order[block] = next(self._tick)
        return True

    def reset_prefix_cache(self) -> None:
        """Drop every cached-but-unreferenced block and all content keys
        (referenced blocks stay allocated, but lose their keys and will
        return to the plain free list)."""
        self._free.extend(self._evictable)
        self._evictable.clear()
        self._hash_to_block.clear()
        self._block_hash.clear()
        self._fifo_order.clear()
