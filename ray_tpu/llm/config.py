"""Engine configuration for ray_tpu.llm.

Everything here exists to keep XLA's compiled-program count O(1): fixed
decode batch slots, a fixed block-table width, and a small set of
power-of-two prefill buckets. The paged cache trades a static
[num_blocks, block_size, H, D] pool for per-sequence dynamic lengths —
the standard continuous-batching layout (vLLM-style) restated under
XLA's static-shape constraint.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class KVFabricConfig:
    """Fleet-wide KV fabric: a shared host-DRAM spill tier for KV blocks.

    One named store actor (`kv_fabric:{name}`) per fabric holds evicted /
    drained blocks keyed by their content chain hash, bounded by
    `byte_budget` with its own LRU. Engines pointing at the same `name`
    share one logical prefix cache: eviction and drain demote blocks to
    the fabric instead of destroying them, and admission restores fabric
    hits into freshly allocated device slots.
    """

    # Fabric identity: engines with the same name share one store actor.
    name: str = "default"
    # Host-DRAM byte budget for the store's own LRU. Must hold at least
    # one block (checked against the actual per-block byte size at engine
    # construction, where the model dims are known).
    byte_budget: int = 64 * 1024 * 1024
    # Prefix-affinity routing: serve.build_app layers a consistent hash on
    # the prompt's leading block-chain hash onto the router's p2c pick, so
    # multi-turn sessions land where their cache already lives. Routing
    # only — the spill/restore tier works either way.
    affinity: bool = True
    # Bound on every store RPC (single-block put/get/contains/stats; the
    # batch put_many gets 6x — it moves a whole drain flush). A call that
    # exceeds it degrades to a miss/no-op and bumps
    # llm_engine_fabric_timeouts: the fabric is an accelerator, and a
    # HUNG store actor must stall admission/eviction no longer than a
    # dead one would.
    rpc_timeout_s: float = 5.0

    def __post_init__(self):
        if self.rpc_timeout_s <= 0:
            raise ValueError(
                f"kv_fabric.rpc_timeout_s must be > 0, got "
                f"{self.rpc_timeout_s} — an unbounded store RPC lets a "
                "hung store actor stall the engine step loop"
            )
        if not self.name:
            raise ValueError(
                "kv_fabric.name must be non-empty — it names the shared "
                "store actor (kv_fabric:{name}) engines rendezvous on"
            )
        if self.byte_budget < 1:
            raise ValueError(
                f"kv_fabric.byte_budget must be >= 1 byte, got "
                f"{self.byte_budget} — a fabric that can hold nothing "
                "silently degrades every spill to a discard"
            )


# Engine roles for disaggregated prefill/decode. A "prefill" engine runs
# chunked prefill only, publishes each finished block to the fabric, and
# finishes the request at its first token; a "decode" engine admits the
# handed-off request as a pure fabric hit and generates the rest.
ENGINE_ROLES = ("unified", "prefill", "decode")


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    # Cache layout. Block 0 is reserved as the null/trash block: block
    # tables pad with it, and masked lanes scatter into it.
    block_size: int = 8
    num_blocks: int = 128
    # Decode runs one jitted program over exactly this many slots; idle
    # slots compute against the null block and are ignored.
    max_decode_slots: int = 8
    # Static width of every block table; bounds sequence length at
    # max_blocks_per_seq * block_size tokens.
    max_blocks_per_seq: int = 16
    # Prefill lengths are padded up to one of these (multiples of
    # block_size); derived as powers of two up to max_model_len if empty.
    prefill_buckets: Tuple[int, ...] = ()
    # How many queued prompts may be prefilled in a single engine step.
    max_prefills_per_step: int = 1
    # Chunked prefill: per-step budget of prompt tokens fed through the
    # prefill programs. Long prompts are split into block-aligned chunks
    # fed through the (already bucketed) partial-prefill programs, one
    # chunk interleaved alongside the decode batch per engine iteration —
    # so a long prompt streams in over several steps instead of
    # monopolizing one, and decode time-per-output-token stays flat.
    # Greedy outputs are token-identical with the budget set or unset.
    #   -1   ("auto", the default): a block-aligned budget of roughly a
    #        quarter of max_model_len (never below one block).
    #   0 / None: chunking off — every prompt prefills in one dispatch,
    #        exactly the pre-chunking behavior.
    #   N > 0: explicit budget; must be a multiple of block_size.
    max_prefill_tokens_per_step: Optional[int] = -1
    # Default generation bound when a request does not specify one.
    default_max_new_tokens: int = 32
    # Automatic prefix caching: full KV blocks are content-addressed
    # (chain-hashed token ids) and freed blocks stay reusable until
    # evicted, so shared system prompts, repeated prompts, and
    # preempt-resume re-prefills skip recomputing the cached prefix.
    # Greedy outputs are token-identical either way.
    enable_prefix_caching: bool = True
    # Which cached-but-unreferenced block to evict under pressure:
    # "lru" (least recently freed/used) or "fifo" (oldest registration).
    prefix_eviction_policy: str = "lru"
    # Poison-request isolation: a step exception attributable to a single
    # request dead-letters only that request (its KV blocks are released
    # and the loop keeps stepping; an isolated failure does not count
    # toward the threshold below). After this many CONSECUTIVE failing
    # steps with no isolatable culprit the engine declares itself wedged:
    # check_health() flips false and the error is broadcast to every
    # waiter so the Serve controller replaces the replica.
    max_consecutive_step_failures: int = 3
    # How many dead-letter records (id, prompt hash, error) to retain.
    dead_letter_capacity: int = 64
    # Paged-attention implementation for the decode / partial-prefill
    # programs: "pallas" runs the fused block-table-walking kernel
    # (ops.paged_flash — block gather, QK^T, masking, online softmax and
    # weighted-V in one pass), "reference" the XLA gather+softmax op, and
    # "auto" picks pallas on TPU, reference elsewhere. Greedy outputs are
    # token-identical across implementations in the acceptance tests
    # (f32, CPU interpret mode); on TPU in bf16 the two take different
    # rounding paths (the kernel pre-scales q in storage dtype, the
    # reference scales f32 logits), so near-tie argmax flips are
    # possible, as with any kernel swap. Warmup compiles every bucket
    # program with whichever implementation is selected.
    attn_impl: str = "auto"
    # KV-cache pool storage: "auto" follows the model dtype, "bf16"
    # forces bfloat16, and "int8" stores quantized pools with per-token
    # per-head scales (ops.paged_flash.quantize_kv) — roughly half the
    # bytes per cached token, so ~1.9x the sequences fit the same pool
    # and continuous batching keeps more requests in flight. Outputs are
    # within quantization tolerance of bf16; greedy argmax is expected to
    # match on typical prompts but is not bit-guaranteed.
    kv_cache_dtype: str = "auto"
    # Decode-time sampling policy. Only "greedy" (argmax) is implemented;
    # the knob exists so speculative decoding can reject non-greedy
    # configurations explicitly until rejection sampling lands.
    sampling: str = "greedy"
    # Speculative decoding (ray_tpu.llm.spec): "off" decodes one token per
    # sequence per step; "ngram" proposes continuations by matching the
    # sequence's own token history against its tail (prompt lookup — no
    # draft model, pure host-side matching); "draft" runs a second,
    # smaller GPT (draft_model_config) through the same runner harness.
    # Either way the target model scores all k proposed tokens in ONE
    # verify step against the paged KV cache, accepts the longest agreeing
    # prefix plus the correction/bonus token, and rolls back rejected
    # tokens (block-table trim + context-length rewind) — so greedy
    # outputs are token-identical with speculation on or off, and each
    # verify step emits between 1 and k+1 tokens. (Under
    # kv_cache_dtype="int8" the identity inherits int8's own
    # within-quantization-tolerance contract — the caveat partial
    # prefill already carries.)
    speculation: str = "off"
    # How many tokens a proposer may run ahead per verify step (k). The
    # verify program is compiled per fed-width bucket (1 + proposed,
    # powers of two up to k); each sequence speculates at most
    # min(k, its remaining budget - 1, cache capacity).
    num_speculative_tokens: int = 4
    # n-gram proposer: longest/shortest history suffix to match. Longer
    # matches are tried first (higher precision), falling back to shorter.
    ngram_max: int = 3
    ngram_min: int = 1
    # GPTConfig of the draft model (required iff speculation="draft").
    # It must satisfy max_seq_len >= max_model_len, like the target.
    draft_model_config: Optional[Any] = None
    # Intra-replica tensor parallelism: the number of chips one engine
    # replica spans. 1 (the default) is the single-chip path, bit-for-bit
    # unchanged. > 1 builds a `tp` mesh over the first N backend devices
    # (ray_tpu.parallel.tensor_parallel_mesh) and runs every jitted
    # program SPMD over it: GPT weights shard Megatron-style (qkv/mlp-in
    # column-parallel, attn-out/mlp-out row-parallel — one psum per block
    # after each row-parallel projection), and the paged KV pools, int8
    # scale pools, and the draft-model mirror pool all shard on the HEAD
    # axis, so each chip's paged_flash instance DMAs only its local heads'
    # cache blocks while the allocator/prefix cache/scheduler stay
    # host-global (block ids are shard-invariant). Requires num_heads of
    # the target AND draft model to be divisible by this, and at least
    # this many backend devices — both checked fail-fast at construction.
    # Both attn_impl values are supported (the implementation runs
    # head-sliced under shard_map either way). Greedy outputs are
    # token-identical to tensor_parallel_size=1 in the acceptance tests
    # (f32, CPU host-device mesh); on TPU in bf16 the partial-sum
    # reduction order differs, so near-tie argmax flips are possible — the
    # same contract as any kernel swap.
    tensor_parallel_size: int = 1
    # Fleet-wide KV fabric (ray_tpu.llm.kvfabric): None (the default)
    # disables every fabric hook and leaves all existing paths bit-for-bit
    # unchanged. A KVFabricConfig turns evictions and drains into demotion
    # (device pool -> host-DRAM store keyed by chain hash) and extends the
    # admission prefix match past the device cache into the fabric.
    kv_fabric: Optional[KVFabricConfig] = None
    # Disaggregated prefill/decode role: "unified" (default) serves both
    # phases; "prefill" runs chunked prefill only, publishing finished
    # blocks to the fabric and completing at the first token; "decode"
    # expects handed-off requests whose prefix blocks are fabric hits.
    # Both non-unified roles require kv_fabric.
    engine_role: str = "unified"
    # Async double-buffered step loop: split each decode step into a
    # dispatch phase and a deferred commit phase, pipelined one step deep.
    # While step N's decode program runs on device, the host plans and
    # dispatches step N+1 with step N's on-device `next_tokens` chained
    # directly into N+1's token input (positions/context_lens advance +1
    # deterministically); an async device->host copy brings N's values
    # back for emission one step behind. Consequences: EOS/max-token
    # finishes are detected one step late (the overshoot token is
    # committed to a scratch position and never emitted), verify/spec
    # steps and batch-composition changes are pipeline-flush boundaries
    # (commit-before-plan), and a poisoned decode commit surfaces one
    # step after its dispatch (failure records attribute against the
    # dispatch index). Greedy outputs are token-identical either way;
    # False (the default) keeps the synchronous loop bit-for-bit.
    async_scheduling: bool = False
    # Bounded admission: cap the scheduler backlog so overload fails fast
    # at submission instead of queueing without bound. None (the default)
    # keeps the waiting deque unbounded — bit-for-bit the pre-overload-
    # control behavior. With a cap set, a submission that would push the
    # backlog past max_queue_len requests (or max_queue_tokens queued
    # prompt tokens, counting running prefills' remaining tokens) is
    # rejected with a typed, retryable EngineOverloadedError carrying a
    # retry-after hint; every rejection lands in the shed ring
    # (LLMEngine.shed_requests()) and bumps llm_engine_shed_requests.
    max_queue_len: Optional[int] = None
    max_queue_tokens: Optional[int] = None
    # How many shed records (id, reason, queue depth) to retain.
    shed_capacity: int = 64
    # Per-request observability: lifecycle phase spans (queue/prefill/
    # decode/preempt via util.tracing), the TTFT / time-per-output-token /
    # queue / e2e / step-seconds histograms, and the per-step flight-
    # recorder ring. False compiles it all out of the step loop (coarse
    # engine gauges/counters and failure records remain).
    instrument: bool = True
    # How many per-step flight-recorder records to retain.
    flight_recorder_capacity: int = 256

    @property
    def max_model_len(self) -> int:
        return self.max_blocks_per_seq * self.block_size

    @property
    def num_usable_blocks(self) -> int:
        return self.num_blocks - 1  # block 0 is the null block

    def buckets(self) -> Tuple[int, ...]:
        if self.prefill_buckets:
            return tuple(sorted(self.prefill_buckets))
        out, b = [], self.block_size
        while b < self.max_model_len:
            out.append(b)
            b *= 2
        out.append(self.max_model_len)
        return tuple(out)

    def __post_init__(self):
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is reserved)")
        if self.max_decode_slots < 1:
            raise ValueError("max_decode_slots must be >= 1")
        if self.max_consecutive_step_failures < 1:
            raise ValueError("max_consecutive_step_failures must be >= 1")
        if self.dead_letter_capacity < 1:
            raise ValueError("dead_letter_capacity must be >= 1")
        if self.flight_recorder_capacity < 1:
            raise ValueError("flight_recorder_capacity must be >= 1")
        if self.shed_capacity < 1:
            raise ValueError("shed_capacity must be >= 1")
        if self.max_queue_len is not None and self.max_queue_len < 1:
            raise ValueError(
                f"max_queue_len must be >= 1 or None (unbounded), got "
                f"{self.max_queue_len} — a zero cap would shed every request"
            )
        if self.max_queue_tokens is not None and self.max_queue_tokens < 1:
            raise ValueError(
                f"max_queue_tokens must be >= 1 or None (unbounded), got "
                f"{self.max_queue_tokens}"
            )
        budget = self.max_prefill_tokens_per_step
        if budget is not None and budget > 0:
            if budget % self.block_size:
                raise ValueError(
                    f"max_prefill_tokens_per_step {budget} is not a "
                    f"multiple of block_size {self.block_size} — chunks "
                    "must be block-aligned so non-final chunks fill whole "
                    "blocks (prefix-cache publication and CoW depend on it)"
                )
        elif budget is not None and budget not in (0, -1):
            raise ValueError(
                "max_prefill_tokens_per_step must be -1 (auto), 0/None "
                f"(off), or a positive multiple of block_size; got {budget}"
            )
        if self.tensor_parallel_size < 1:
            raise ValueError(
                "tensor_parallel_size must be >= 1, got "
                f"{self.tensor_parallel_size}"
            )
        if self.attn_impl not in ("auto", "pallas", "reference"):
            raise ValueError(
                "attn_impl must be one of ('auto', 'pallas', 'reference'), "
                f"got {self.attn_impl!r}"
            )
        if self.kv_cache_dtype not in ("auto", "bf16", "int8"):
            raise ValueError(
                "kv_cache_dtype must be one of ('auto', 'bf16', 'int8'), "
                f"got {self.kv_cache_dtype!r}"
            )
        if self.speculation not in ("off", "ngram", "draft"):
            raise ValueError(
                "speculation must be one of ('off', 'ngram', 'draft'), "
                f"got {self.speculation!r}"
            )
        if self.sampling != "greedy":
            if self.speculation != "off":
                # Rejection sampling for stochastic decoding is not
                # implemented: verification compares proposals against the
                # target's argmax, which is only correct for greedy.
                raise ValueError(
                    "speculative decoding requires greedy sampling until "
                    "rejection sampling is supported; got "
                    f"sampling={self.sampling!r} with "
                    f"speculation={self.speculation!r}"
                )
            raise ValueError(
                "sampling must be 'greedy' (the only implemented policy), "
                f"got {self.sampling!r}"
            )
        if self.num_speculative_tokens < 1:
            raise ValueError(
                "num_speculative_tokens must be >= 1, got "
                f"{self.num_speculative_tokens}"
            )
        if (
            self.speculation != "off"
            and self.num_speculative_tokens >= self.max_model_len
        ):
            raise ValueError(
                f"num_speculative_tokens {self.num_speculative_tokens} "
                f"must be < max_model_len {self.max_model_len} (a sequence "
                "can never verify more tokens than the cache can hold)"
            )
        if self.ngram_min < 1:
            raise ValueError("ngram_min must be >= 1")
        if self.ngram_max < self.ngram_min:
            raise ValueError(
                f"ngram_max ({self.ngram_max}) must be >= ngram_min "
                f"({self.ngram_min})"
            )
        if self.speculation == "draft" and self.draft_model_config is None:
            raise ValueError(
                'speculation="draft" requires draft_model_config (the '
                "draft GPTConfig)"
            )
        if self.speculation != "draft" and self.draft_model_config is not None:
            raise ValueError(
                "draft_model_config is only meaningful with "
                f'speculation="draft" (got speculation={self.speculation!r});'
                " a silently-ignored draft model is a misconfiguration"
            )
        if self.engine_role not in ENGINE_ROLES:
            raise ValueError(
                f"engine_role must be one of {ENGINE_ROLES}, got "
                f"{self.engine_role!r}"
            )
        if self.engine_role == "prefill":
            if self.kv_fabric is None:
                raise ValueError(
                    'engine_role="prefill" requires kv_fabric: a prefill '
                    "engine's only output is the KV blocks it publishes — "
                    "without a fabric the decode engine can never see them"
                )
            if self.prefill_token_budget is None:
                raise ValueError(
                    'engine_role="prefill" requires chunked prefill '
                    "(max_prefill_tokens_per_step must not be 0/None): "
                    "the prefill role publishes blocks as chunks complete, "
                    "which is the chunked path's block-aligned contract"
                )
        if self.engine_role == "decode" and self.kv_fabric is None:
            raise ValueError(
                'engine_role="decode" requires kv_fabric: a decode engine '
                "admits handed-off requests as fabric hits — without a "
                "fabric every handoff silently degrades to a full re-prefill"
            )
        from ray_tpu.llm.cache import EVICTION_POLICIES

        if self.prefix_eviction_policy not in EVICTION_POLICIES:
            raise ValueError(
                f"prefix_eviction_policy must be one of {EVICTION_POLICIES},"
                f" got {self.prefix_eviction_policy!r}"
            )
        for b in self.prefill_buckets:
            if b % self.block_size:
                raise ValueError(
                    f"prefill bucket {b} is not a multiple of block_size "
                    f"{self.block_size}"
                )
            if b > self.max_model_len:
                raise ValueError(
                    f"prefill bucket {b} exceeds max_model_len "
                    f"{self.max_model_len}"
                )

    @property
    def prefill_token_budget(self) -> Optional[int]:
        """The resolved per-step prefill token budget: None when chunking
        is off (0/None), the explicit value when set, or — for -1 (auto) —
        a block-aligned quarter of max_model_len, never below one block."""
        v = self.max_prefill_tokens_per_step
        if not v:  # 0 or None: chunking off
            return None
        if v == -1:
            quarter = (self.max_model_len // 4) // self.block_size
            return max(1, quarter) * self.block_size
        return v

    def chunk_widths(self) -> Tuple[int, ...]:
        """The prefill buckets the chunked path can dispatch: every chunk
        feeds at most prefill_token_budget tokens, so only buckets up to
        bucket_for(budget) are reachable — warmup compiles exactly this
        set (larger full-prefill programs can never run under a budget),
        and lint RTL805 judges the table against the bucket table. With
        chunking off this is the whole bucket table."""
        budget = self.prefill_token_budget
        if budget is None:
            return self.buckets()
        # A budget at or above the largest bucket can't restrict anything:
        # admission already bounds every prefill to the largest bucket, so
        # the whole table stays reachable.
        cap = self.bucket_for(min(budget, self.buckets()[-1]))
        return tuple(b for b in self.buckets() if b <= cap)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets():
            if b >= n:
                return b
        raise ValueError(
            f"prompt of {n} tokens exceeds max_model_len {self.max_model_len}"
        )

    def verify_buckets(self) -> Tuple[int, ...]:
        """Fed-token widths (1 + proposed tokens, proposal counts bucketed
        to powers of two up to num_speculative_tokens) the k-token verify
        program compiles — O(log k) programs, warmed at init like the
        prefill buckets. Empty when speculation is off."""
        if self.speculation == "off":
            return ()
        out, b = [], 1
        while b < self.num_speculative_tokens:
            out.append(1 + b)
            b *= 2
        out.append(1 + self.num_speculative_tokens)
        return tuple(out)

    def verify_bucket_for(self, n_fed: int) -> int:
        for b in self.verify_buckets():
            if b >= n_fed:
                return b
        raise ValueError(
            f"verify step of {n_fed} fed tokens exceeds the largest verify "
            f"bucket (num_speculative_tokens={self.num_speculative_tokens})"
        )
