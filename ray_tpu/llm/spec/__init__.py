"""ray_tpu.llm.spec — speculative decoding proposers.

Decode emits one token per target-model step; speculation turns that into
"guess k tokens cheaply, score them all in ONE target step, keep the
longest agreeing prefix plus the correction/bonus token". The guessing is
pluggable (Proposer): NgramProposer matches the sequence's own token
history (prompt lookup — free, shines on repetitive text), and
DraftModelProposer runs a smaller GPT through the same runner harness
(costs draft compute, generalizes to novel text). Verification
(GPTRunner.verify + the engine's rollback) guarantees greedy outputs are
token-identical with speculation on or off; proposers only change speed.

Select via EngineConfig(speculation="ngram"|"draft", ...); see
llm/config.py for the knobs and llm/engine.py for the verify phase.
"""

from ray_tpu.llm.spec.proposer import NgramProposer, Proposer


def build_proposer(engine_config, seed: int = 0, draft_params=None):
    """The proposer EngineConfig.speculation selects (None when "off").
    `draft_params` optionally supplies trained draft weights; without
    them the draft model initializes from `seed` like the target."""
    if engine_config.speculation == "off":
        return None
    if engine_config.speculation == "ngram":
        return NgramProposer(
            ngram_max=engine_config.ngram_max,
            ngram_min=engine_config.ngram_min,
        )
    # "draft" (validated by EngineConfig.__post_init__). Deferred import:
    # the draft path is the only one that needs the model stack.
    from ray_tpu.llm.spec.draft import DraftModelProposer

    return DraftModelProposer(
        engine_config.draft_model_config,
        engine_config,
        params=draft_params,
        seed=seed,
    )


__all__ = [
    "NgramProposer",
    "Proposer",
    "build_proposer",
]
