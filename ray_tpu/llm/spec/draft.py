"""Draft-model proposer: a second, smaller GPT through the runner harness.

The draft shares everything with the target path except the weights: the
same GPTRunner (jitted prefill / partial-prefill / decode programs over a
paged cache), its own block pool (same geometry as the target's, so the
admission math is identical), and recompute-style state discipline — a
released sequence simply re-prefills from its committed tokens.

Per verify step the proposer (1) catches the draft cache up on the tokens
the target committed since last time (the accepted proposals plus the
correction/bonus token) via the draft's own partial-prefill program, whose
final argmax doubles as the FIRST proposal, then (2) runs k-1 batched
draft decode steps chaining proposals, and (3) rewinds its committed-token
count — proposal K/V stays in the draft blocks as garbage above the
committed length (masked by context_len) until the next catch-up
overwrites it, exactly the target engine's rollback discipline.

The draft cache never feeds the target model: a draft of any quality only
changes how many proposals survive verification, never the output.

Tensor parallelism rides through for free: the draft's GPTRunner receives
the SAME engine config, so at tensor_parallel_size > 1 its weights shard
Megatron-style and its mirror pool shards on its own head axis over the
same `tp` mesh — which is why the draft model's num_heads must also
divide the tp degree (validated fail-fast, with a draft-naming error, in
LLMEngine before anything is built).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.llm.cache import BlockAllocator, blocks_for_tokens
from ray_tpu.llm.spec.proposer import Proposer


class _DraftSeq:
    """Draft-side mirror of one running sequence: its draft block table
    and how many committed tokens the draft cache holds K/V for."""

    __slots__ = ("block_table", "num_cached")

    def __init__(self):
        self.block_table: List[int] = []
        self.num_cached = 0


class DraftModelProposer(Proposer):
    name = "draft"

    def __init__(
        self,
        draft_model_config,
        engine_config,
        params=None,
        seed: int = 0,
    ):
        # Deferred import: model_runner pulls in jax/flax, which the
        # host-only ngram path must never pay for.
        from ray_tpu.llm.model_runner import GPTRunner

        self.engine_config = engine_config
        self.runner = GPTRunner(
            draft_model_config, engine_config, params=params, seed=seed
        )
        # Prefix caching off: draft state is private per sequence and the
        # engine's own prefix cache already de-duplicates target compute;
        # a second content-addressed map would only complicate release().
        self.allocator = BlockAllocator(
            engine_config.num_blocks,
            engine_config.block_size,
            enable_prefix_caching=False,
        )
        self._state: Dict[str, _DraftSeq] = {}

    # ---------------- Proposer interface ----------------

    def propose(self, seqs, k: int) -> List[List[int]]:
        ecfg = self.engine_config
        props: List[List[int]] = [[] for _ in seqs]
        chain: List[tuple] = []  # (out_index, seq_len, budget, _DraftSeq)
        for i, seq in enumerate(seqs):
            ids = seq.prefill_ids
            n = len(ids)
            # Proposals past the model length or the request's remaining
            # token budget (minus the always-emitted bonus slot) can never
            # be verified — the target trims them, so chaining them would
            # be pure wasted draft dispatches. Chain writes land at
            # positions n .. n + budget - 2.
            budget = min(
                k,
                ecfg.max_model_len - n,
                seq.request.max_new_tokens - len(seq.generated) - 1,
            )
            if budget < 1:
                continue
            budget = self._reserve(seq, n, budget)
            if budget < 1:
                continue
            st = self._state[seq.request.request_id]
            first = self._catch_up(ids, st)
            if first is None:
                continue
            props[i].append(first)
            if budget > 1:
                chain.append((i, n, budget, st))
        # Chain the remaining proposals with BATCHED draft decode steps:
        # every still-active sequence advances one draft token per
        # iteration through the same [max_decode_slots] program the
        # target compiles.
        slots = ecfg.max_decode_slots
        nb = ecfg.max_blocks_per_seq
        for t in range(1, k):
            live = [
                (i, n, st)
                for (i, n, budget, st) in chain
                if t < budget
                and len(props[i]) == t
                and self._covers(st, n + t)
            ]
            if not live:
                break
            tokens = np.zeros((slots,), np.int32)
            positions = np.zeros((slots,), np.int32)
            tables = np.zeros((slots, nb), np.int32)
            ctx = np.zeros((slots,), np.int32)
            for j, (i, n, st) in enumerate(live):
                tokens[j] = props[i][-1]
                positions[j] = n + t - 1
                tables[j, : len(st.block_table)] = st.block_table
                ctx[j] = n + t - 1
            next_tokens = self.runner.decode(tokens, positions, tables, ctx)
            for j, (i, n, st) in enumerate(live):
                props[i].append(int(next_tokens[j]))
        return props

    def release(self, request_id: str) -> None:
        st = self._state.pop(request_id, None)
        if st is not None and st.block_table:
            self.allocator.free(st.block_table)

    def warmup(self) -> None:
        """Compile the draft's programs against the null block (writes to
        block 0 are the masked-lane convention — harmless garbage): every
        prefill bucket, the partial-prefill bucket a catch-up lands in,
        and the batched decode step."""
        ecfg = self.engine_config
        for bucket in ecfg.buckets():
            n = min(bucket, ecfg.max_model_len - 1)
            if n < 1:
                continue
            self.runner.prefill([0] * n, [0] * blocks_for_tokens(n, ecfg.block_size))
            self.runner.prefill_suffix([0] * n, [0], 0)
        slots = ecfg.max_decode_slots
        self.runner.decode(
            np.zeros((slots,), np.int32),
            np.zeros((slots,), np.int32),
            np.zeros((slots, ecfg.max_blocks_per_seq), np.int32),
            np.zeros((slots,), np.int32),
        )

    # ---------------- internals ----------------

    def _covers(self, st: _DraftSeq, tokens: int) -> bool:
        """Whether st's blocks cover a write at position tokens - 1."""
        return len(st.block_table) * self.allocator.block_size >= tokens

    def _reserve(self, seq, n: int, budget: int) -> int:
        """Extend (or create) the draft block table to hold the committed
        `n` tokens plus the proposal chain's writes (positions
        n .. n + budget - 2), shrinking the budget — never evicting
        another sequence's draft state — under pool pressure. Returns the
        affordable budget; 0 releases this sequence's draft state."""
        rid = seq.request.request_id
        bs = self.allocator.block_size
        st = self._state.get(rid)
        if st is None:
            st = _DraftSeq()
            self._state[rid] = st
        while budget >= 1:
            target = blocks_for_tokens(max(n + budget - 1, n), bs)
            extra = target - len(st.block_table)
            if extra <= 0:
                return budget
            if self.allocator.can_allocate(extra):
                # ray-tpu: lint-ignore[RTL404] allocate is pre-checked
                # (cannot raise) and its result lands directly in
                # st.block_table, which release() frees — there is no
                # statement in between for an exception to leak through
                st.block_table.extend(self.allocator.allocate(extra))
                return budget
            budget -= 1
        # Not even the committed tokens fit: drop the mirror; the next
        # propose() retries from scratch under (hopefully) less pressure.
        self.release(rid)
        return 0

    def _catch_up(self, ids: List[int], st: _DraftSeq) -> Optional[int]:
        """Feed the draft the committed tokens it has not seen (the whole
        prompt on first contact or after a release; the accepted tokens
        since, otherwise). The final argmax is the first proposal."""
        n = len(ids)
        if st.num_cached >= n:
            # The engine commits at least one token per step, so the
            # delta is never empty between propose() calls; an equal
            # count means propose() was re-run on unchanged state (step
            # retry) — re-feed the last token to recompute the proposal.
            st.num_cached = n - 1
        delta = ids[st.num_cached :]
        try:
            if st.num_cached == 0:
                # The mirror table is sized for the committed tokens
                # PLUS the proposal chain (_reserve), but the prefill
                # program's block vector holds exactly bucket_for(n) //
                # block_size entries — feed only the blocks the tokens
                # occupy, or the scatter buffer rejects the extra ids
                # and the except below silently skips proposing
                # whenever n sits at a bucket boundary and the chain
                # spills into the next block (first contact and every
                # post-release re-prefill).
                nb = blocks_for_tokens(n, self.allocator.block_size)
                first = self.runner.prefill(ids, st.block_table[:nb])
            else:
                first = self.runner.prefill_suffix(
                    delta, st.block_table, st.num_cached
                )
        except ValueError:
            # Delta outgrew the draft's bucket table (possible only with
            # custom prefill_buckets smaller than max_model_len): skip
            # proposing rather than failing the engine step.
            return None
        st.num_cached = n
        return int(first)
