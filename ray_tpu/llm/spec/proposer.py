"""Proposer interface + the n-gram / prompt-lookup proposer.

A proposer guesses the next k tokens of each running sequence; the engine
then scores all k guesses in ONE target-model step (GPTRunner.verify) and
keeps the longest agreeing prefix. Proposals therefore only affect SPEED,
never output: a bad guess costs one rejected lane, a good one amortizes a
full decode step across several tokens. Greedy outputs are token-identical
with any proposer (or none).

NgramProposer is pure host-side token matching — no model, no device work,
no jitted calls — so it adds nothing to the step loop's host-device
pipeline (and is deliberately outside lint RTL503's host-sync rule, which
targets syncs on jitted results).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Sequence as SeqType

if TYPE_CHECKING:  # pragma: no cover - import cycle guard, typing only
    from ray_tpu.llm.scheduler import Sequence


class Proposer:
    """Pluggable speculative-token source (ray_tpu.llm.spec).

    The engine calls `propose` once per verify step with every decoding
    sequence, and `release` whenever a sequence stops running (finish,
    abort, dead-letter, preemption) so stateful proposers drop any
    per-request resources. Implementations must be deterministic: a
    retried engine step re-runs propose() from unchanged scheduler state
    and must get the same proposals back.
    """

    #: Reported through stats()/flight records.
    name = "base"

    def propose(
        self, seqs: SeqType["Sequence"], k: int
    ) -> List[List[int]]:
        """Up to k proposed continuation tokens per sequence, aligned with
        `seqs`. An empty list means "no guess" — that sequence falls back
        to a plain one-token step inside the verify program."""
        raise NotImplementedError

    def release(self, request_id: str) -> None:
        """Drop per-request proposer state (no-op for stateless ones)."""

    def warmup(self) -> None:
        """Compile any device programs the proposer owns (no-op for
        host-only proposers); called from LLMServer init-time warmup."""


class NgramProposer(Proposer):
    """Prompt-lookup decoding: match the sequence's last n tokens against
    an earlier occurrence in its own history (prompt + generated) and
    propose the tokens that followed that occurrence. No draft model: the
    bet is that generation revisits its own context — quoting the prompt,
    repeating boilerplate, continuing a list — which is exactly where
    decode throughput hurts most. Pure host-side list matching; cost is
    O(history * ngram_max) per sequence per step.
    """

    name = "ngram"

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"[{ngram_min}, {ngram_max}]"
            )
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min

    def propose(self, seqs, k: int) -> List[List[int]]:
        return [
            self.match(seq.request.prompt_ids + seq.generated, k)
            for seq in seqs
        ]

    def match(self, tokens: List[int], k: int) -> List[int]:
        """Longest-n-first prompt lookup: the continuation after an
        earlier occurrence of the tail n-gram, truncated to k tokens.
        Among occurrences of the same n-gram, the most recent one with a
        FULL k-token continuation wins (recent context predicts best);
        occurrences near the end of the history — whose continuation is
        cut short by the history itself, as in short-period repetition —
        are kept only as a fallback, longest continuation first."""
        if k < 1:
            return []
        n_tokens = len(tokens)
        for n in range(self.ngram_max, self.ngram_min - 1, -1):
            if n_tokens <= n:
                continue
            tail = tokens[-n:]
            best: List[int] = []
            # Right-to-left: the first full-k match is the most recent.
            for start in range(n_tokens - n - 1, -1, -1):
                if tokens[start : start + n] == tail:
                    # start <= n_tokens - n - 1, so the continuation is
                    # never empty (it may overlap the tail: the match
                    # then predicts the repetition continuing).
                    cont = tokens[start + n : start + n + k]
                    if len(cont) == k:
                        return list(cont)
                    if len(cont) > len(best):
                        best = list(cont)
            if best:
                return best
        return []
