"""Jitted prefill / decode step functions over the paged KV cache.

XLA compiles O(1) programs: one decode program (fixed [max_decode_slots]
batch, fixed block-table width), one full-prefill program per power-of-two
bucket, one *partial*-prefill program per bucket (prefix caching: feed only
the uncached suffix at a position offset and attend to the cached prefix
through the block table — paged attention over the prefix, causal over the
suffix), one block-to-block copy (copy-on-write for shared blocks), and —
with speculative decoding on — one batched k-token verify program per fed
width bucket (the partial-prefill shape generalized to [max_decode_slots]
slots with per-slot position offsets, returning the argmax at EVERY fed
position so the engine can accept the longest agreeing proposal prefix).
The cache pools are [L, num_blocks, block_size, H, D] device arrays
threaded functionally through every step with donated buffers, so steps
update the cache in place without host round-trips.

Serving hot-path knobs (EngineConfig):

  * ``attn_impl`` — the decode / partial-prefill programs read the cache
    either through the fused Pallas kernel (``ops.paged_flash``: the block
    table is walked inside the kernel pipeline, gather + QK^T + masking +
    online softmax + weighted-V in one pass) or the XLA gather+softmax
    reference. "auto" resolves once at construction: pallas on TPU,
    reference elsewhere. Warmup compiles every bucket program with whatever
    was resolved, so the kernel never cold-compiles under live traffic.
  * ``kv_cache_dtype`` — "int8" stores the pools quantized with per-token
    per-head scale tensors [L, N, bs, H] (scales ride every scatter and
    block copy); dequantization is fused into the attention op. ~1.9x the
    sequences fit the same pool bytes.
  * ``tensor_parallel_size`` — > 1 builds a `tp` mesh over the backend
    devices and runs ALL FIVE programs SPMD over it: weights shard
    Megatron-style from the model's logical axis annotations, the cache /
    scale pools shard on the HEAD axis (the axis ``paged_flash`` already
    loops over, so each chip's kernel instance DMAs only its local heads'
    cache blocks), attention runs head-sliced under shard_map, and the
    donated pool buffers stay sharded through every step (the returned
    pools carry an explicit sharding constraint, so donation aliases
    buffer-for-buffer and nothing ever gathers). Block ids are
    shard-invariant — the allocator/scheduler stay host-global.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.llm.cache import kv_pool_bytes_sharded
from ray_tpu.llm.config import EngineConfig
from ray_tpu.models.gpt import GPT, GPTConfig, collect_kv_caches
from ray_tpu.ops.attention import validate_tp_heads
from ray_tpu.ops.paged_flash import (
    KV_SCALE_DTYPE,
    quantize_kv,
    resolve_paged_impl,
)


class _StepPrograms:
    """The five jitted programs for one (model geometry, block size,
    attention impl, KV dtype, tensor-parallel degree) configuration.

    Shared process-wide through `_step_programs`: jax's compilation cache
    keys on the *callable*, so per-runner bound methods recompile
    everything for every engine instance — a replica restart, a draft
    model, every test engine. One `_StepPrograms` per config makes each
    (program, shapes) pair compile once per process; a same-config runner
    built later warms up through pure cache hits. Entries hold only
    config-derived state (the model *definition*, mesh, pool sharding) —
    never params or pools — so a cached entry costs bytes, not HBM.
    """

    def __init__(
        self,
        model_config: GPTConfig,
        block_size: int,
        attn_impl: str,
        kv_cache_dtype,
        tensor_parallel_size: int,
    ):
        self.model_config = model_config
        self.block_size = block_size
        self.attn_impl = attn_impl
        self.kv_cache_dtype = kv_cache_dtype
        self.quantized = kv_cache_dtype == jnp.int8
        self.model = GPT(model_config)
        if tensor_parallel_size > 1:
            from ray_tpu.parallel.mesh import tensor_parallel_mesh
            from ray_tpu.parallel.sharding import llm_pool_sharding

            self.mesh = tensor_parallel_mesh(tensor_parallel_size)
            self.pool_sharding = llm_pool_sharding(self.mesh)
        else:
            self.mesh = None
            self.pool_sharding = None
        self.decode_fn = jax.jit(
            self._decode_step, donate_argnums=(1, 2, 3, 4)
        )
        self.verify_fn = jax.jit(
            self._verify_step, donate_argnums=(1, 2, 3, 4)
        )
        self.prefill_fn = jax.jit(
            self._prefill_step, donate_argnums=(1, 2, 3, 4)
        )
        self.prefill_suffix_fn = jax.jit(
            self._prefill_suffix_step, donate_argnums=(1, 2, 3, 4)
        )
        self.copy_block_fn = jax.jit(
            self._copy_block_step, donate_argnums=(0, 1, 2, 3)
        )
        self.restore_block_fn = jax.jit(
            self._restore_block_step, donate_argnums=(0, 1, 2, 3)
        )

    # ---------------- traced helpers ----------------

    def _constrain_pools(self, pools):
        """Pin the returned pools to the head-sharded layout inside every
        jitted program: the constraint makes the donated input buffers and
        the outputs provably alias (same shape, dtype AND sharding), so no
        step can silently reshard — or worse, gather — a pool."""
        if self.pool_sharding is None:
            return pools
        return tuple(
            p
            if p is None
            else jax.lax.with_sharding_constraint(p, self.pool_sharding)
            for p in pools
        )

    def _paged_caches(self, k_cache, v_cache, k_scale, v_scale,
                      block_tables, context_lens):
        return (k_cache, v_cache, block_tables, context_lens, k_scale,
                v_scale)

    def _store_kv(self, new_kv: jax.Array) -> Tuple[jax.Array, Optional[jax.Array]]:
        """New-token K or V [..., H, D] → (pool-dtype values, per-token
        scales or None). int8 pools quantize at scatter time — per-token
        scales are what a single-token decode write can maintain."""
        if self.quantized:
            return quantize_kv(new_kv)
        return new_kv.astype(self.kv_cache_dtype), None

    # ---------------- the five step programs ----------------

    def _prefill_step(
        self, params, k_cache, v_cache, k_scale, v_scale, tokens, blocks,
        true_len,
    ):
        """tokens [1, S_bucket], blocks [S_bucket // bs] (0-padded),
        true_len scalar → (pools, next_token)."""
        cfg = self.model_config
        logits, state = self.model.apply(
            params, tokens, return_kv=True, mutable=["intermediates"],
            paged_mesh=self.mesh,
        )
        kvs = collect_kv_caches(state["intermediates"], cfg.num_layers)
        s = tokens.shape[1]
        nb = s // self.block_size
        paged = (nb, self.block_size, cfg.num_heads, cfg.head_dim)
        for layer, (k, v) in enumerate(kvs):
            kq, ks = self._store_kv(k[0])
            vq, vs = self._store_kv(v[0])
            k_cache = k_cache.at[layer, blocks].set(kq.reshape(paged))
            v_cache = v_cache.at[layer, blocks].set(vq.reshape(paged))
            if ks is not None:
                k_scale = k_scale.at[layer, blocks].set(
                    ks.reshape(paged[:-1])
                )
                v_scale = v_scale.at[layer, blocks].set(
                    vs.reshape(paged[:-1])
                )
        next_token = jnp.argmax(logits[0, true_len - 1, :]).astype(jnp.int32)
        pools = self._constrain_pools((k_cache, v_cache, k_scale, v_scale))
        return pools, next_token

    def _prefill_suffix_step(
        self, params, k_cache, v_cache, k_scale, v_scale, tokens,
        block_table, offset, true_len,
    ):
        """tokens [1, S_bucket] uncached suffix (0-padded), block_table
        [max_blocks_per_seq] the sequence's full table (0-padded), offset
        scalar = cached prefix length, true_len scalar = real suffix length
        → (pools, next_token).

        One program per suffix bucket: the suffix attends to the cached
        prefix through the block table (paged) and to itself causally, and
        its K/V is scattered token-by-token at positions offset..offset+S-1
        (padded lanes land in the null block)."""
        cfg = self.model_config
        sb = tokens.shape[1]
        lane = jnp.arange(sb)
        valid = lane < true_len
        positions = jnp.where(valid, offset + lane, 0)
        logits, state = self.model.apply(
            params,
            tokens,
            positions=positions[None, :],
            paged_caches=self._paged_caches(
                k_cache, v_cache, k_scale, v_scale,
                block_table[None, :], jnp.reshape(offset, (1,)),
            ),
            paged_impl=self.attn_impl,
            paged_mesh=self.mesh,
            mutable=["intermediates"],
        )
        kvs = collect_kv_caches(state["intermediates"], cfg.num_layers)
        bs = self.block_size
        block_ids = jnp.where(valid, block_table[positions // bs], 0)
        offsets = jnp.where(valid, positions % bs, 0)
        for layer, (k, v) in enumerate(kvs):
            kq, ks = self._store_kv(k[0])
            vq, vs = self._store_kv(v[0])
            k_cache = k_cache.at[layer, block_ids, offsets].set(kq)
            v_cache = v_cache.at[layer, block_ids, offsets].set(vq)
            if ks is not None:
                k_scale = k_scale.at[layer, block_ids, offsets].set(ks)
                v_scale = v_scale.at[layer, block_ids, offsets].set(vs)
        next_token = jnp.argmax(logits[0, true_len - 1, :]).astype(jnp.int32)
        pools = self._constrain_pools((k_cache, v_cache, k_scale, v_scale))
        return pools, next_token

    def _restore_block_step(
        self, k_cache, v_cache, k_scale, v_scale, dst, k, v, ks, vs
    ):
        """Write one spilled block's content back into slot `dst` — the KV
        fabric restore path. A scatter of host payloads, not a new model
        program: under tensor parallelism the sharding constraint re-pins
        the pools head-sharded, so a restore can never deshard the cache."""
        k_cache = k_cache.at[:, dst].set(k)
        v_cache = v_cache.at[:, dst].set(v)
        if k_scale is not None:
            k_scale = k_scale.at[:, dst].set(ks)
            v_scale = v_scale.at[:, dst].set(vs)
        return self._constrain_pools((k_cache, v_cache, k_scale, v_scale))

    def _copy_block_step(self, k_cache, v_cache, k_scale, v_scale, src, dst):
        k_cache = k_cache.at[:, dst].set(k_cache[:, src])
        v_cache = v_cache.at[:, dst].set(v_cache[:, src])
        if k_scale is not None:
            # int8 pools: a block copy must carry the dequant scales too,
            # or the CoW copy would be read back at the wrong magnitude.
            k_scale = k_scale.at[:, dst].set(k_scale[:, src])
            v_scale = v_scale.at[:, dst].set(v_scale[:, src])
        return self._constrain_pools((k_cache, v_cache, k_scale, v_scale))

    def _decode_step(
        self, params, k_cache, v_cache, k_scale, v_scale, tokens, positions,
        block_tables, context_lens,
    ):
        """One iteration-level decode over all slots. tokens/positions [B],
        block_tables [B, nb], context_lens [B] → (pools, next_tokens [B])."""
        bs = self.block_size
        b = tokens.shape[0]
        logits, state = self.model.apply(
            params,
            tokens[:, None],
            positions=positions[:, None],
            paged_caches=self._paged_caches(
                k_cache, v_cache, k_scale, v_scale, block_tables, context_lens
            ),
            paged_impl=self.attn_impl,
            paged_mesh=self.mesh,
            mutable=["intermediates"],
        )
        kvs = collect_kv_caches(
            state["intermediates"], self.model_config.num_layers
        )
        # Scatter each slot's new-token K/V at its absolute position. Idle
        # slots carry an all-null block table, so they land in block 0.
        block_ids = block_tables[jnp.arange(b), positions // bs]
        offsets = positions % bs
        for layer, (k, v) in enumerate(kvs):
            kq, ks = self._store_kv(k[:, 0])
            vq, vs = self._store_kv(v[:, 0])
            k_cache = k_cache.at[layer, block_ids, offsets].set(kq)
            v_cache = v_cache.at[layer, block_ids, offsets].set(vq)
            if ks is not None:
                k_scale = k_scale.at[layer, block_ids, offsets].set(ks)
                v_scale = v_scale.at[layer, block_ids, offsets].set(vs)
        next_tokens = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        pools = self._constrain_pools((k_cache, v_cache, k_scale, v_scale))
        return pools, next_tokens

    def _verify_step(
        self, params, k_cache, v_cache, k_scale, v_scale, tokens,
        block_tables, context_lens, true_lens,
    ):
        """Batched multi-token scoring for speculative decoding. tokens
        [B, S] = each slot's last committed token followed by its proposed
        tokens (0-padded past true_lens[b]); block_tables [B, nb];
        context_lens [B] = committed K/V per slot; true_lens [B] = fed
        tokens per slot (1 + that slot's proposals) → (pools, out [B, S]).

        The batched generalization of the partial-prefill program: slot b's
        fed tokens sit at absolute positions context_lens[b] + lane, attend
        the committed prefix through the block table (paged) and each other
        causally, and their K/V is scattered at those positions — so
        out[b, i], the argmax after consuming fed tokens 0..i, is exactly
        the token the plain decode loop would have produced at that point.
        int8 caveat: lanes attend EACH OTHER through their fresh
        full-precision K/V (new_k/new_v), while sequential decode reads
        the same tokens back quantized — the identical caveat partial
        prefill already carries — so under kv_cache_dtype="int8" the
        equivalence is within quantization tolerance (argmax-identical on
        the tested prompt set, not bit-guaranteed), exactly int8's own
        contract.
        Padded lanes (lane >= true_lens[b]) scatter into the null block and
        their outputs are garbage the engine never reads. The engine
        commits the longest proposal prefix agreeing with `out` and rolls
        the rest back (Scheduler.rollback); rejected lanes' K/V stays
        masked above the rewound context length."""
        cfg = self.model_config
        b, s = tokens.shape
        lane = jnp.arange(s)[None, :]
        valid = lane < true_lens[:, None]  # [B, S]
        positions = jnp.where(valid, context_lens[:, None] + lane, 0)
        logits, state = self.model.apply(
            params,
            tokens,
            positions=positions,
            paged_caches=self._paged_caches(
                k_cache, v_cache, k_scale, v_scale, block_tables,
                context_lens,
            ),
            paged_impl=self.attn_impl,
            paged_mesh=self.mesh,
            mutable=["intermediates"],
        )
        kvs = collect_kv_caches(state["intermediates"], cfg.num_layers)
        bs = self.block_size
        rows = jnp.arange(b)[:, None]
        block_ids = jnp.where(
            valid, block_tables[rows, positions // bs], 0
        )
        offsets = jnp.where(valid, positions % bs, 0)
        for layer, (k, v) in enumerate(kvs):
            kq, ks = self._store_kv(k)
            vq, vs = self._store_kv(v)
            k_cache = k_cache.at[layer, block_ids, offsets].set(kq)
            v_cache = v_cache.at[layer, block_ids, offsets].set(vq)
            if ks is not None:
                k_scale = k_scale.at[layer, block_ids, offsets].set(ks)
                v_scale = v_scale.at[layer, block_ids, offsets].set(vs)
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        pools = self._constrain_pools((k_cache, v_cache, k_scale, v_scale))
        return pools, out


_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_LOCK = threading.Lock()


def _step_programs(
    model_config: GPTConfig,
    block_size: int,
    attn_impl: str,
    kv_cache_dtype,
    tensor_parallel_size: int,
) -> _StepPrograms:
    """Process-wide config-keyed cache of `_StepPrograms`. The key is
    everything the traced programs close over: the (frozen, hashable)
    model config, the block size (the only EngineConfig field the traced
    bodies read — all other geometry arrives through argument shapes, which
    jax's own cache keys on), the resolved attention impl and pool dtype,
    and the tp degree (the mesh is deterministic given the backend's
    devices, which are fixed for the process). A constructor failure (e.g.
    tp exceeding the device count) propagates without caching."""
    key = (
        model_config,
        block_size,
        attn_impl,
        np.dtype(kv_cache_dtype).name,
        tensor_parallel_size,
    )
    with _PROGRAM_CACHE_LOCK:
        programs = _PROGRAM_CACHE.get(key)
        if programs is None:
            programs = _StepPrograms(
                model_config, block_size, attn_impl, kv_cache_dtype,
                tensor_parallel_size,
            )
            _PROGRAM_CACHE[key] = programs
    return programs


class GPTRunner:
    """Owns the params, the paged cache pools, and the compiled steps."""

    def __init__(
        self,
        model_config: GPTConfig,
        engine_config: EngineConfig,
        params=None,
        seed: int = 0,
    ):
        if engine_config.max_model_len > model_config.max_seq_len:
            raise ValueError(
                f"cache capacity {engine_config.max_model_len} tokens/seq "
                f"exceeds model max_seq_len {model_config.max_seq_len}"
            )
        self.model_config = model_config
        self.engine_config = engine_config
        # Intra-replica tensor parallelism: one mesh with a `tp` axis over
        # the first tensor_parallel_size backend devices; None at tp=1 so
        # the single-chip path stays bit-for-bit unchanged (no device_put,
        # no sharding constraints, no shard_map anywhere below).
        self.tensor_parallel_size = engine_config.tensor_parallel_size
        validate_tp_heads(model_config.num_heads, self.tensor_parallel_size)

        # Resolved once: the jitted programs below bake the choice in.
        self.attn_impl = resolve_paged_impl(engine_config.attn_impl)
        self.kv_cache_dtype = {
            "auto": model_config.dtype,
            "bf16": jnp.bfloat16,
            "int8": jnp.int8,
        }[engine_config.kv_cache_dtype]
        self.quantized = self.kv_cache_dtype == jnp.int8
        # What the pools actually store, in the knob's vocabulary —
        # observability reports this, not the configured string, so
        # "auto" never leaks to dashboards.
        self.kv_cache_dtype_str = {
            jnp.bfloat16: "bf16", jnp.int8: "int8"
        }.get(self.kv_cache_dtype, jnp.dtype(self.kv_cache_dtype).name)

        # The compiled step programs (and the mesh/model/sharding they
        # close over) come from the process-wide config-keyed cache: a
        # same-config runner built later — replica restart, draft model,
        # another test engine — reuses the already-compiled executables.
        self._programs = _step_programs(
            model_config,
            engine_config.block_size,
            self.attn_impl,
            self.kv_cache_dtype,
            self.tensor_parallel_size,
        )
        self.model = self._programs.model
        self.mesh = self._programs.mesh
        self._pool_sharding = self._programs.pool_sharding
        if params is None:
            probe = jnp.zeros((1, engine_config.block_size), jnp.int32)
            if self.mesh is not None:
                # Seed-init on the host CPU: the full tree must never
                # materialize on one accelerator chip (a tp-sharded model
                # may exceed per-chip HBM — the situation tp exists for).
                # llm_shard_params below then device_puts each leaf
                # straight from host memory into its Megatron placement,
                # the same host->shards path a numpy checkpoint takes.
                with jax.default_device(jax.local_devices(backend="cpu")[0]):
                    params = self.model.init(jax.random.PRNGKey(seed), probe)
            else:
                params = self.model.init(jax.random.PRNGKey(seed), probe)
        if self.mesh is not None:
            # Megatron-style weight placement from the model's logical axis
            # annotations (parallel.sharding.LLM_TP_RULES): qkv/mlp-in
            # column-parallel, attn-out/mlp-out row-parallel, embeddings
            # and norms replicated. Works on freshly-initialized boxed
            # params and on user checkpoints alike.
            from ray_tpu.parallel.sharding import llm_shard_params

            params = llm_shard_params(self.mesh, params)
        self.params = params
        # Parameter count, once at init (a tree reduce over the weights is
        # too slow for a stats() scrape): feeds the fleet ledger's MFU
        # estimate — decode FLOPs ~= 2 * num_params per generated token.
        self.num_params = int(
            sum(x.size for x in jax.tree_util.tree_leaves(params))
        )
        # Host-transfer accounting: bytes explicitly moved across the
        # host/device boundary by the program dispatches below (token ids,
        # block tables, lengths in; sampled token ids out). The pools and
        # params never appear here — they live donated on the device(s) —
        # so these counters are flat in tensor_parallel_size by
        # construction. They are the accounting half of the no-gather
        # claim; the detection half is pool_sharding_spec() (a desharded
        # pool after traffic) plus the compiled-HLO gate in
        # tests/test_llm_tp.py, which asserts the tp=2 decode executable
        # contains zero all-gather ops (a dropped output-sharding
        # constraint makes GSPMD gather the pools right there).
        self.host_bytes_in = 0
        self.host_bytes_out = 0

        cfg, ecfg = model_config, engine_config
        cache_shape = (
            cfg.num_layers,
            ecfg.num_blocks,
            ecfg.block_size,
            cfg.num_heads,
            cfg.head_dim,
        )
        self.k_cache = self._zeros_pool(cache_shape, self.kv_cache_dtype)
        self.v_cache = self._zeros_pool(cache_shape, self.kv_cache_dtype)
        if self.quantized:
            scale_shape = cache_shape[:-1]  # [L, N, bs, H]
            self.k_scale = self._zeros_pool(scale_shape, KV_SCALE_DTYPE)
            self.v_scale = self._zeros_pool(scale_shape, KV_SCALE_DTYPE)
        else:
            self.k_scale = None
            self.v_scale = None
        self._decode_fn = self._programs.decode_fn
        self._verify_fn = self._programs.verify_fn
        self._prefill_fn = self._programs.prefill_fn
        self._prefill_suffix_fn = self._programs.prefill_suffix_fn
        self._copy_block_fn = self._programs.copy_block_fn
        self._restore_block_fn = self._programs.restore_block_fn

    # ---------------- pool plumbing ----------------

    def _zeros_pool(self, shape, dtype):
        """Allocate one device pool — under tensor parallelism it is
        assembled shard-by-shard in the head-sharded layout, so the full
        pool never materializes on a single chip (a tp-sharded pool may
        exceed per-chip HBM — the very situation tp exists for)."""
        if self._pool_sharding is None:
            return jnp.zeros(shape, dtype)

        def shard_zeros(index):
            shard_shape = tuple(
                len(range(*idx.indices(dim)))
                for idx, dim in zip(index, shape)
            )
            return np.zeros(shard_shape, np.dtype(dtype))

        return jax.make_array_from_callback(
            shape, self._pool_sharding, shard_zeros
        )

    @property
    def _pools(self):
        return (self.k_cache, self.v_cache, self.k_scale, self.v_scale)

    def _set_pools(self, pools) -> None:
        self.k_cache, self.v_cache, self.k_scale, self.v_scale = pools

    def _count_transfer(self, arrays_in, out) -> None:
        self.host_bytes_in += sum(int(a.nbytes) for a in arrays_in)
        self.host_bytes_out += int(out.nbytes)

    def host_transfer_bytes(self) -> int:
        """Cumulative explicit host<->device bytes across all program
        dispatches (inputs fed + sampled tokens fetched). Per-step deltas
        land in the flight-recorder step records; the tp parity tests
        assert the series is identical at tensor_parallel_size 1 and 2."""
        return self.host_bytes_in + self.host_bytes_out

    def pool_sharding_spec(self) -> Optional[str]:
        """The live K-pool's PartitionSpec as a string (None at tp=1):
        observability surfaces it, and tests assert it still names the
        head axis after serving traffic — proof no step desharded the
        cache."""
        if self.mesh is None:
            return None
        return str(self.k_cache.sharding.spec)

    def kv_pool_bytes(self) -> dict:
        """Aggregate and per-shard bytes of both KV pools (+ scale tensors
        when quantized): per-chip HBM is aggregate / tensor_parallel_size
        because the pools shard on the head axis."""
        cfg, ecfg = self.model_config, self.engine_config
        return kv_pool_bytes_sharded(
            cfg.num_layers,
            ecfg.num_blocks,
            ecfg.block_size,
            cfg.num_heads,
            cfg.head_dim,
            np.dtype(self.kv_cache_dtype).itemsize,
            np.dtype(KV_SCALE_DTYPE).itemsize if self.quantized else None,
            tensor_parallel_size=self.tensor_parallel_size,
        )

    # ---------------- prefill ----------------

    def prefill(self, token_ids: Sequence[int], block_ids: Sequence[int]) -> int:
        """Run one prompt through the model, scatter its K/V into the given
        blocks, and return the greedily-sampled next token."""
        ecfg = self.engine_config
        n = len(token_ids)
        bucket = ecfg.bucket_for(n)
        nb = bucket // ecfg.block_size
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = token_ids
        # Bucket padding beyond the sequence's own blocks scatters into the
        # null block; it is garbage that nothing ever reads unmasked.
        blocks = np.zeros((nb,), np.int32)
        blocks[: len(block_ids)] = block_ids
        pools, next_token = self._prefill_fn(
            self.params,
            *self._pools,
            jnp.asarray(tokens),
            jnp.asarray(blocks),
            jnp.int32(n),
        )
        self._set_pools(pools)
        self._count_transfer((tokens, blocks), next_token)
        return int(next_token)

    # ---------------- partial prefill (prefix caching) ----------------

    def prefill_suffix(
        self, token_ids: Sequence[int], block_ids: Sequence[int], offset: int
    ) -> int:
        """Prefix-aware prefill: run only the uncached suffix of a prompt
        whose first `offset` tokens already sit in the paged cache (through
        `block_ids`, the sequence's whole block table), scatter the suffix
        K/V, and return the greedily-sampled next token."""
        ecfg = self.engine_config
        n = len(token_ids)
        bucket = ecfg.bucket_for(n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = token_ids
        table = np.zeros((ecfg.max_blocks_per_seq,), np.int32)
        table[: len(block_ids)] = block_ids
        pools, next_token = self._prefill_suffix_fn(
            self.params,
            *self._pools,
            jnp.asarray(tokens),
            jnp.asarray(table),
            jnp.int32(offset),
            jnp.int32(n),
        )
        self._set_pools(pools)
        self._count_transfer((tokens, table), next_token)
        return int(next_token)

    def copy_block(self, src: int, dst: int) -> None:
        """Device-copy one block's K/V (and scales) across every layer
        (copy-on-write before a sequence writes into a shared block).
        Under tensor parallelism the copy is shard-local: src and dst
        address the same blocks on every chip, each chip copies its own
        heads' slice (scales included)."""
        self._set_pools(
            self._copy_block_fn(*self._pools, jnp.int32(src), jnp.int32(dst))
        )
        self.host_bytes_in += 8  # two int32 block ids

    # ---------------- KV fabric spill / restore ----------------

    def kv_block_bytes(self) -> int:
        """Bytes of ONE block's payload (K + V values across every layer,
        plus scale tensors when quantized) — what a single fabric entry
        costs, and the floor the fabric byte budget is validated against."""
        cfg, ecfg = self.model_config, self.engine_config
        slots = cfg.num_layers * ecfg.block_size * cfg.num_heads
        nbytes = 2 * slots * cfg.head_dim * np.dtype(self.kv_cache_dtype).itemsize
        if self.quantized:
            nbytes += 2 * slots * np.dtype(KV_SCALE_DTYPE).itemsize
        return nbytes

    def extract_block(self, block: int) -> dict:
        """Read one block's device content to host numpy — the spill half
        of the fabric tier. The payload is pool-dtype values (+ int8
        scales), so restore is bit-exact; `kv_dtype` stamps the storage
        format so a mismatched engine treats the entry as a miss instead
        of scattering garbage."""
        payload = {
            "kv_dtype": self.kv_cache_dtype_str,
            "k": np.asarray(self.k_cache[:, block]),
            "v": np.asarray(self.v_cache[:, block]),
        }
        if self.quantized:
            payload["k_scale"] = np.asarray(self.k_scale[:, block])
            payload["v_scale"] = np.asarray(self.v_scale[:, block])
        self.host_bytes_out += sum(
            int(a.nbytes) for a in payload.values() if hasattr(a, "nbytes")
        )
        return payload

    def restore_block(self, block: int, payload: dict) -> None:
        """Write one spilled payload back into slot `block` — the restore
        half. Raises ValueError on a storage-format mismatch (different
        kv_cache_dtype or geometry); the caller must then free the slot
        and treat the chain as a fabric miss."""
        if payload.get("kv_dtype") != self.kv_cache_dtype_str:
            raise ValueError(
                f"fabric payload stored as {payload.get('kv_dtype')!r}, "
                f"pool is {self.kv_cache_dtype_str!r} — engines on one "
                "fabric must share kv_cache_dtype"
            )
        k, v = payload["k"], payload["v"]
        expected = self.k_cache.shape[:1] + self.k_cache.shape[2:]
        if k.shape != expected:
            raise ValueError(
                f"fabric payload block shape {k.shape} does not match "
                f"pool block shape {expected}"
            )
        if self.quantized:
            ks = jnp.asarray(payload["k_scale"])
            vs = jnp.asarray(payload["v_scale"])
        else:
            ks = vs = None
        self._set_pools(
            self._restore_block_fn(
                *self._pools,
                jnp.int32(block),
                jnp.asarray(k),
                jnp.asarray(v),
                ks,
                vs,
            )
        )
        self.host_bytes_in += sum(
            int(a.nbytes) for a in payload.values() if hasattr(a, "nbytes")
        )

    # ---------------- decode / k-token verification ----------------

    def verify(
        self,
        tokens: np.ndarray,
        block_tables: np.ndarray,
        context_lens: np.ndarray,
        true_lens: np.ndarray,
    ) -> np.ndarray:
        """Score up to S-1 proposed tokens per slot in one step (see
        _verify_step). Arrays must already be padded to
        [max_decode_slots, S_bucket] / [max_decode_slots, max_blocks_per_seq]
        / [max_decode_slots]; one program compiles per S bucket
        (EngineConfig.verify_buckets)."""
        pools, out = self._verify_fn(
            self.params,
            *self._pools,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(context_lens, jnp.int32),
            jnp.asarray(true_lens, jnp.int32),
        )
        self._set_pools(pools)
        out = np.asarray(out)
        self._count_transfer(
            (tokens, block_tables, context_lens, true_lens), out
        )
        return out

    def decode(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        block_tables: np.ndarray,
        context_lens: np.ndarray,
    ) -> np.ndarray:
        """Batched single-token decode; arrays must already be padded to
        [max_decode_slots] / [max_decode_slots, max_blocks_per_seq]."""
        pools, next_tokens = self._decode_fn(
            self.params,
            *self._pools,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(context_lens, jnp.int32),
        )
        self._set_pools(pools)
        next_tokens = np.asarray(next_tokens)
        self._count_transfer(
            (tokens, positions, block_tables, context_lens), next_tokens
        )
        return next_tokens

    def decode_async(
        self,
        tokens,
        positions: np.ndarray,
        block_tables: np.ndarray,
        context_lens: np.ndarray,
    ) -> jax.Array:
        """Dispatch one batched decode WITHOUT waiting for its result.

        Same compiled program as `decode` (identical avals, so no extra
        compile), but the sampled tokens stay on device: `tokens` may be
        the previous step's on-device `next_tokens` (token chaining — it
        is not donated, so the caller can still fetch it afterwards), and
        the return value is the device array for THIS step with an async
        device->host copy already started. The caller materializes the
        values one step later with `np.asarray` at commit time.

        The host-side numpy inputs are converted with `jnp.array`
        (guaranteed copy): the engine reuses these buffers across steps,
        and a zero-copy alias would let next step's buffer fill corrupt a
        still-running program's inputs.
        """
        chained = isinstance(tokens, jax.Array)
        pools, next_tokens = self._decode_fn(
            self.params,
            *self._pools,
            tokens if chained else jnp.array(tokens, jnp.int32),
            jnp.array(positions, jnp.int32),
            jnp.array(block_tables, jnp.int32),
            jnp.array(context_lens, jnp.int32),
        )
        self._set_pools(pools)
        try:
            next_tokens.copy_to_host_async()
        except (AttributeError, NotImplementedError):  # pragma: no cover
            pass  # backend without async copies: the commit asarray blocks
        # Chained token inputs never cross the host boundary — that is
        # part of the win the transfer counters should show.
        host_in = (positions, block_tables, context_lens)
        if not chained:
            host_in = (tokens,) + host_in
        self._count_transfer(host_in, next_tokens)
        return next_tokens
