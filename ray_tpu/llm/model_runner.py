"""Jitted prefill / decode step functions over the paged KV cache.

XLA compiles O(1) programs: one decode program (fixed [max_decode_slots]
batch, fixed block-table width), one full-prefill program per power-of-two
bucket, one *partial*-prefill program per bucket (prefix caching: feed only
the uncached suffix at a position offset and attend to the cached prefix
through the block table — paged attention over the prefix, causal over the
suffix), and one block-to-block copy (copy-on-write for shared blocks).
The cache pools are [L, num_blocks, block_size, H, D] device arrays
threaded functionally through every step with donated buffers, so steps
update the cache in place without host round-trips.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.llm.config import EngineConfig
from ray_tpu.models.gpt import GPT, GPTConfig, collect_kv_caches


class GPTRunner:
    """Owns the params, the paged cache pools, and the compiled steps."""

    def __init__(
        self,
        model_config: GPTConfig,
        engine_config: EngineConfig,
        params=None,
        seed: int = 0,
    ):
        if engine_config.max_model_len > model_config.max_seq_len:
            raise ValueError(
                f"cache capacity {engine_config.max_model_len} tokens/seq "
                f"exceeds model max_seq_len {model_config.max_seq_len}"
            )
        self.model_config = model_config
        self.engine_config = engine_config
        self.model = GPT(model_config)
        if params is None:
            probe = jnp.zeros((1, engine_config.block_size), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), probe)
        self.params = params

        cfg, ecfg = model_config, engine_config
        cache_shape = (
            cfg.num_layers,
            ecfg.num_blocks,
            ecfg.block_size,
            cfg.num_heads,
            cfg.head_dim,
        )
        self.k_cache = jnp.zeros(cache_shape, cfg.dtype)
        self.v_cache = jnp.zeros(cache_shape, cfg.dtype)
        self._decode_fn = jax.jit(self._decode_step, donate_argnums=(1, 2))
        self._prefill_fn = jax.jit(self._prefill_step, donate_argnums=(1, 2))
        self._prefill_suffix_fn = jax.jit(
            self._prefill_suffix_step, donate_argnums=(1, 2)
        )
        self._copy_block_fn = jax.jit(
            self._copy_block_step, donate_argnums=(0, 1)
        )

    # ---------------- prefill ----------------

    def _prefill_step(self, params, k_cache, v_cache, tokens, blocks, true_len):
        """tokens [1, S_bucket], blocks [S_bucket // bs] (0-padded),
        true_len scalar → (k_cache, v_cache, next_token)."""
        cfg, ecfg = self.model_config, self.engine_config
        logits, state = self.model.apply(
            params, tokens, return_kv=True, mutable=["intermediates"]
        )
        kvs = collect_kv_caches(state["intermediates"], cfg.num_layers)
        s = tokens.shape[1]
        nb = s // ecfg.block_size
        for layer, (k, v) in enumerate(kvs):
            paged = (nb, ecfg.block_size, cfg.num_heads, cfg.head_dim)
            k_cache = k_cache.at[layer, blocks].set(
                k[0].reshape(paged).astype(k_cache.dtype)
            )
            v_cache = v_cache.at[layer, blocks].set(
                v[0].reshape(paged).astype(v_cache.dtype)
            )
        next_token = jnp.argmax(logits[0, true_len - 1, :]).astype(jnp.int32)
        return k_cache, v_cache, next_token

    def prefill(self, token_ids: Sequence[int], block_ids: Sequence[int]) -> int:
        """Run one prompt through the model, scatter its K/V into the given
        blocks, and return the greedily-sampled next token."""
        ecfg = self.engine_config
        n = len(token_ids)
        bucket = ecfg.bucket_for(n)
        nb = bucket // ecfg.block_size
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = token_ids
        # Bucket padding beyond the sequence's own blocks scatters into the
        # null block; it is garbage that nothing ever reads unmasked.
        blocks = np.zeros((nb,), np.int32)
        blocks[: len(block_ids)] = block_ids
        self.k_cache, self.v_cache, next_token = self._prefill_fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(blocks),
            jnp.int32(n),
        )
        return int(next_token)

    # ---------------- partial prefill (prefix caching) ----------------

    def _prefill_suffix_step(
        self, params, k_cache, v_cache, tokens, block_table, offset, true_len
    ):
        """tokens [1, S_bucket] uncached suffix (0-padded), block_table
        [max_blocks_per_seq] the sequence's full table (0-padded), offset
        scalar = cached prefix length, true_len scalar = real suffix length
        → (k_cache, v_cache, next_token).

        One program per suffix bucket: the suffix attends to the cached
        prefix through the block table (paged) and to itself causally, and
        its K/V is scattered token-by-token at positions offset..offset+S-1
        (padded lanes land in the null block)."""
        cfg, ecfg = self.model_config, self.engine_config
        sb = tokens.shape[1]
        lane = jnp.arange(sb)
        valid = lane < true_len
        positions = jnp.where(valid, offset + lane, 0)
        logits, state = self.model.apply(
            params,
            tokens,
            positions=positions[None, :],
            paged_caches=(
                k_cache,
                v_cache,
                block_table[None, :],
                jnp.reshape(offset, (1,)),
            ),
            mutable=["intermediates"],
        )
        kvs = collect_kv_caches(state["intermediates"], cfg.num_layers)
        bs = ecfg.block_size
        block_ids = jnp.where(valid, block_table[positions // bs], 0)
        offsets = jnp.where(valid, positions % bs, 0)
        for layer, (k, v) in enumerate(kvs):
            k_cache = k_cache.at[layer, block_ids, offsets].set(
                k[0].astype(k_cache.dtype)
            )
            v_cache = v_cache.at[layer, block_ids, offsets].set(
                v[0].astype(v_cache.dtype)
            )
        next_token = jnp.argmax(logits[0, true_len - 1, :]).astype(jnp.int32)
        return k_cache, v_cache, next_token

    def prefill_suffix(
        self, token_ids: Sequence[int], block_ids: Sequence[int], offset: int
    ) -> int:
        """Prefix-aware prefill: run only the uncached suffix of a prompt
        whose first `offset` tokens already sit in the paged cache (through
        `block_ids`, the sequence's whole block table), scatter the suffix
        K/V, and return the greedily-sampled next token."""
        ecfg = self.engine_config
        n = len(token_ids)
        bucket = ecfg.bucket_for(n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = token_ids
        table = np.zeros((ecfg.max_blocks_per_seq,), np.int32)
        table[: len(block_ids)] = block_ids
        self.k_cache, self.v_cache, next_token = self._prefill_suffix_fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(table),
            jnp.int32(offset),
            jnp.int32(n),
        )
        return int(next_token)

    def _copy_block_step(self, k_cache, v_cache, src, dst):
        k_cache = k_cache.at[:, dst].set(k_cache[:, src])
        v_cache = v_cache.at[:, dst].set(v_cache[:, src])
        return k_cache, v_cache

    def copy_block(self, src: int, dst: int) -> None:
        """Device-copy one block's K/V across every layer (copy-on-write
        before a sequence writes into a block it shares)."""
        self.k_cache, self.v_cache = self._copy_block_fn(
            self.k_cache, self.v_cache, jnp.int32(src), jnp.int32(dst)
        )

    # ---------------- decode ----------------

    def _decode_step(
        self, params, k_cache, v_cache, tokens, positions, block_tables,
        context_lens,
    ):
        """One iteration-level decode over all slots. tokens/positions [B],
        block_tables [B, nb], context_lens [B] → (k_cache, v_cache,
        next_tokens [B])."""
        cfg = self.model_config
        bs = self.engine_config.block_size
        b = tokens.shape[0]
        logits, state = self.model.apply(
            params,
            tokens[:, None],
            positions=positions[:, None],
            paged_caches=(k_cache, v_cache, block_tables, context_lens),
            mutable=["intermediates"],
        )
        kvs = collect_kv_caches(state["intermediates"], cfg.num_layers)
        # Scatter each slot's new-token K/V at its absolute position. Idle
        # slots carry an all-null block table, so they land in block 0.
        block_ids = block_tables[jnp.arange(b), positions // bs]
        offsets = positions % bs
        for layer, (k, v) in enumerate(kvs):
            k_cache = k_cache.at[layer, block_ids, offsets].set(
                k[:, 0].astype(k_cache.dtype)
            )
            v_cache = v_cache.at[layer, block_ids, offsets].set(
                v[:, 0].astype(v_cache.dtype)
            )
        next_tokens = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        return k_cache, v_cache, next_tokens

    def decode(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        block_tables: np.ndarray,
        context_lens: np.ndarray,
    ) -> np.ndarray:
        """Batched single-token decode; arrays must already be padded to
        [max_decode_slots] / [max_decode_slots, max_blocks_per_seq]."""
        self.k_cache, self.v_cache, next_tokens = self._decode_fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(context_lens, jnp.int32),
        )
        return np.asarray(next_tokens)
