"""Serve integration: proxy → replica → engine actor → paged cache.

The ingress deployment is thin — replicas forward requests to one shared,
named `LLMServer` engine actor, so scaling HTTP replicas does not duplicate
model weights or split the continuous batch. Streaming responses ride the
actor streaming-generator path into Serve's ndjson/`stream=True` plumbing.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu import serve
from ray_tpu.llm.config import EngineConfig
from ray_tpu.llm.engine import LLMServer
from ray_tpu.models.gpt import GPTConfig


def get_or_create_engine_actor(
    engine_name: str = "default",
    model_config: Optional[GPTConfig] = None,
    engine_config: Optional[EngineConfig] = None,
    params=None,
    seed: int = 0,
    max_concurrency: int = 32,
    draft_params=None,
):
    """Named engine actor shared by every ingress replica. With
    `engine_config.speculation="draft"`, `draft_params` carries the draft
    model's trained weights (seed-initialized otherwise)."""
    return (
        ray_tpu.remote(LLMServer)
        .options(
            name=f"llm_engine:{engine_name}",
            get_if_exists=True,
            max_concurrency=max_concurrency,
        )
        .remote(
            model_config, engine_config, params, seed,
            draft_params=draft_params,
        )
    )


def llm_stream_resume(args: tuple, kwargs: dict, items: list):
    """Failover resume policy for LLMIngress token streams (pass as
    `handle.options(stream=True, stream_resume_fn=llm_stream_resume)`).

    When a replica dies mid-stream, the router re-submits the request with
    the token ids the client has already received folded into the prompt,
    so the resumed stream continues exactly where the dead replica stopped
    and the client-visible stream stays contiguous. With prefix caching the
    resumed prefill is mostly cache hits, so a mid-stream failover costs
    roughly one tail-block prefill. Greedy decoding makes the resumed
    continuation token-identical (the same mechanism as recompute-style
    preemption). Returns None when the stream was already complete.

    Note: resuming computes the remaining budget from the request's own
    "max_new_tokens"; requests that rely on the engine-side default should
    set it explicitly to keep failover from restarting the budget."""
    request = dict(args[0])
    generated = [item["token_id"] for item in items]
    max_new = request.get("max_new_tokens")
    eos_id = request.get("eos_id")
    if eos_id is not None and generated and generated[-1] == eos_id:
        return None
    if max_new is not None and len(generated) >= int(max_new):
        return None
    request["prompt_ids"] = list(request["prompt_ids"]) + generated
    if max_new is not None:
        request["max_new_tokens"] = int(max_new) - len(generated)
    # The resumed tail is a fresh engine request: a pinned request_id could
    # collide with the orphaned original still draining on the engine.
    request.pop("request_id", None)
    return (request,) + tuple(args[1:]), kwargs


class LLMIngress:
    """Deployment callable: JSON dict in, generated token ids (or a token
    stream) out.

    Request schema: {"prompt_ids": [int, ...], "max_new_tokens": int?,
    "eos_id": int?, "stream": bool?, "request_id": str?, "timeout_s":
    float?, "stream_idle_timeout_s": float?} — timeout_s is the request's
    END-TO-END deadline on BOTH paths: the engine derives an absolute
    monotonic deadline at submission and enforces it through admission,
    queueing, and decode, so an expired request is dropped with its KV
    (and draft-mirror) blocks reclaimed rather than decoding for a client
    that stopped waiting. stream_idle_timeout_s additionally bounds the
    PER-TOKEN gap on streams — the job timeout_s itself did before the
    overload control plane landed; clients that relied on the old
    per-token meaning should pass stream_idle_timeout_s instead (the old
    field is still accepted, it just means the end-to-end budget now).
    """

    # Minimum gap between engine autoscaling_snapshot RPCs: the controller
    # polls replica metrics every reconcile pass (~50ms) and N replicas
    # share one engine — without the cache the engine's lock would see
    # 20/s x replicas control-plane acquisitions.
    AUTOSCALING_METRICS_TTL_S = 0.25
    # Last-good fallback age cap: past this, a degraded engine's frozen
    # snapshot stops being replayed to the controller as fresh — the
    # autoscaler sees a signal GAP (holds current count) instead of
    # stale absolute values that could pin scale decisions indefinitely.
    AUTOSCALING_METRICS_STALE_S = 5.0

    def __init__(
        self,
        engine_name: str = "default",
        model_config: Optional[GPTConfig] = None,
        engine_config: Optional[EngineConfig] = None,
        params=None,
        seed: int = 0,
        draft_params=None,
        engine_per_replica: bool = False,
    ):
        # engine_per_replica gives THIS replica its own engine actor
        # (unique name suffix — each replica's __init__ runs in its own
        # replica actor) instead of the one shared named engine. That
        # trades weight duplication for replica-local KV caches, which is
        # the configuration where the KV fabric earns its keep: replicas
        # share prefixes through the fabric's host tier + prefix-affinity
        # routing rather than through one engine's device cache.
        self._owns_engine = bool(engine_per_replica)
        if self._owns_engine:
            engine_name = f"{engine_name}-{uuid.uuid4().hex[:8]}"
        self._engine = get_or_create_engine_actor(
            engine_name, model_config, engine_config, params=params,
            seed=seed, draft_params=draft_params,
        )
        self._as_snapshot: Optional[dict] = None
        self._as_snapshot_t = 0.0

    def __call__(self, request: dict):
        if not isinstance(request, dict) or "prompt_ids" not in request:
            raise ValueError(
                'LLM requests must be {"prompt_ids": [...], ...}, got '
                f"{type(request).__name__}"
            )
        prompt_ids = request["prompt_ids"]
        max_new_tokens = request.get("max_new_tokens")
        eos_id = request.get("eos_id")
        request_id = request.get("request_id")
        timeout_s = request.get("timeout_s")
        idle_timeout_s = request.get("stream_idle_timeout_s")
        kwargs = {} if timeout_s is None else {"timeout_s": float(timeout_s)}
        if request.get("stream"):
            if idle_timeout_s is not None:
                kwargs["stream_idle_timeout_s"] = float(idle_timeout_s)
            # A mid-stream client disconnect must be able to abort the
            # engine request (below), and abort is keyed by request_id —
            # pin one now when the client didn't.
            if request_id is None:
                request_id = uuid.uuid4().hex
            engine = self._engine

            def token_stream():
                # Client disconnect propagation: when the proxy/consumer
                # closes this generator before exhaustion (GeneratorExit on
                # stream cancel, or plain GC of an abandoned stream), the
                # engine request is still decoding into its KV blocks — and
                # with speculation=draft, into the draft-mirror blocks too.
                # Abort it so those blocks free immediately instead of the
                # engine generating max_new_tokens for nobody. The engine
                # dispatch happens INSIDE the body: a never-started
                # generator's finally would never run, so submitting here
                # keeps "no consumer ever pulled" from leaking a request
                # the abort could not cover.
                refs = engine.generate_stream.options(
                    num_returns="streaming"
                ).remote(
                    prompt_ids, max_new_tokens, eos_id, request_id, **kwargs
                )
                completed = False
                try:
                    for ref in refs:
                        yield {"token_id": ray_tpu.get(ref)}
                    completed = True
                finally:
                    if not completed:
                        # Fire-and-forget: the abort's outcome is not
                        # actionable here (a finished request no-ops), and
                        # blocking the closing stream thread on a busy
                        # engine's lock would serialize mass-disconnect
                        # cleanup exactly under queueing collapse.
                        try:
                            _ = engine.abort.remote(request_id)
                        except Exception:
                            pass  # engine gone: its pool died with it

            return token_stream()
        return ray_tpu.get(
            self._engine.generate.remote(
                prompt_ids, max_new_tokens, eos_id, request_id, **kwargs
            )
        )

    def metrics(self) -> dict:
        return ray_tpu.get(self._engine.metrics.remote())

    def autoscaling_metrics(self) -> dict:
        """SLO signals for the controller's LLMAutoscalingPolicy, riding
        the replica metrics poll (ReplicaActor.get_metrics calls this):
        the engine's queue-time/TTFT histogram snapshots and prefill
        backlog (LLMServer.autoscaling_snapshot). TTL-cached; on an engine
        timeout the last good snapshot is returned — a busy engine is
        exactly when the autoscaler most needs a (slightly stale) signal,
        not a gap."""
        now = time.monotonic()
        if (
            self._as_snapshot is not None
            and now - self._as_snapshot_t < self.AUTOSCALING_METRICS_TTL_S
        ):
            return self._as_snapshot
        try:
            snap = ray_tpu.get(
                self._engine.autoscaling_snapshot.remote(), timeout=1.0
            )
        except Exception:
            if now - self._as_snapshot_t > self.AUTOSCALING_METRICS_STALE_S:
                return {}
            return self._as_snapshot or {}
        self._as_snapshot = snap
        self._as_snapshot_t = now
        return snap

    def dead_letters(self) -> list:
        """Records of requests failed in isolation after poisoning an
        engine step (see LLMServer.dead_letters)."""
        return ray_tpu.get(self._engine.dead_letters.remote())

    def flight_record(self, steps_limit: Optional[int] = None) -> dict:
        """The engine flight recorder (see LLMServer.flight_record):
        per-step records, warmup compile events, and step failures."""
        return ray_tpu.get(self._engine.flight_record.remote(steps_limit))

    def observability_snapshot(
        self, steps_limit: Optional[int] = None
    ) -> dict:
        """metrics + dead letters + flight recorder in one engine round
        trip (see LLMServer.observability_snapshot) — with speculation on,
        the metrics carry the acceptance-rate story (spec_acceptance_rate,
        spec_tokens_per_verify_step) and the step records the per-step
        proposed/accepted counts."""
        return ray_tpu.get(
            self._engine.observability_snapshot.remote(steps_limit)
        )

    def reset_prefix_cache(self) -> None:
        """Drop the engine's cached-but-unreferenced KV blocks (call after
        swapping served params, whose cached activations would be stale)."""
        ray_tpu.get(self._engine.reset_prefix_cache.remote())

    def shutdown(self) -> None:
        """Drain-path teardown (ReplicaActor.prepare_for_shutdown calls
        this on the DRAINING→STOPPED transition, after in-flight requests
        finished): when this replica OWNS its engine, flush the engine's
        evictable keyed blocks into the KV fabric — the drained replica's
        reusable prefixes survive as fabric entries a surviving replica
        can restore, instead of dying with the engine actor — then stop
        the engine. A shared engine outlives the replica, so there is
        nothing to flush or stop. Every step is best-effort: shutdown
        must complete even with the fabric or engine already gone."""
        if not self._owns_engine:
            return
        try:
            ray_tpu.get(self._engine.flush_kv_fabric.remote(), timeout=30.0)
        except Exception:
            pass
        try:
            ray_tpu.get(self._engine.shutdown.remote(), timeout=10.0)
        except Exception:
            pass
        try:
            ray_tpu.kill(self._engine)
        except Exception:
            pass

    def check_health(self) -> bool:
        """Replica health forwards to the engine, but a busy engine (e.g.
        compiling a new bucket) must read as healthy — the controller's probe
        window is short and killing the replica would not unblock anything.
        Only a dead/raising engine fails the probe (the replacement replica
        then re-creates the named engine actor)."""
        from ray_tpu.exceptions import ActorError

        try:
            healthy = bool(
                ray_tpu.get(self._engine.check_health.remote(), timeout=1.0)
            )
        except TimeoutError:
            return True
        except ActorError:
            return False
        if not healthy:
            # A wedged engine never recovers on its own, and because it is a
            # NAMED actor, merely replacing this replica would hand the
            # replacement the same wedged engine (get_if_exists). Put it
            # down so the replacement replica re-creates it fresh.
            try:
                ray_tpu.kill(self._engine)
            except Exception:
                pass  # already dead / runtime tearing down
        return healthy


def build_app(
    model_config: Optional[GPTConfig] = None,
    engine_config: Optional[EngineConfig] = None,
    *,
    params=None,
    engine_name: Optional[str] = None,
    num_replicas: int = 1,
    max_concurrent_queries: int = 32,
    seed: int = 0,
    draft_params=None,
    autoscaling_config: Any = None,
    graceful_shutdown_timeout_s: Optional[float] = None,
    engine_per_replica: bool = False,
) -> serve.Application:
    """Bind the LLM ingress for `serve.run` (HTTP via the existing proxy:
    POST /<app> with the request JSON). Pass trained weights via `params`;
    without them the engine serves a seed-initialized model.

    `engine_config.tensor_parallel_size > 1` makes the ONE shared engine
    actor span a multi-chip mesh (weights Megatron-sharded, KV pools
    head-sharded — see EngineConfig): scaling `num_replicas` still only
    adds HTTP ingress replicas, never weight copies, and the engine's
    stats()/flight records/autoscaling signals all carry the
    tensor_parallel_size tag plus per-chip pool bytes for the dashboard's
    /api/llm panel. Warmup compiles every bucket program SPMD over the
    mesh before the deployment reports healthy, exactly as at tp=1.

    `autoscaling_config` accepts serve.LLMAutoscalingPolicy (SLO-driven:
    the ingress feeds the engine's queue-time/TTFT histogram windows and
    prefill backlog to the controller) or the queue-depth
    AutoscalingConfig; `graceful_shutdown_timeout_s` bounds how long a
    draining replica's in-flight streams may run before being
    stream-resumed onto surviving replicas.

    Each build_app call gets its own engine actor by default — the engine
    is keyed by `engine_name`, so two apps share one engine (one copy of
    the weights, one continuous batch) only when given the same explicit
    name. Never reuse a name across different model configs/params: the
    first creation wins and later apps would silently serve its weights."""
    if engine_name is None:
        engine_name = uuid.uuid4().hex[:8]
    deployment = serve.deployment(
        LLMIngress,
        name="LLMIngress",
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
    )
    if autoscaling_config is not None or graceful_shutdown_timeout_s is not None:
        deployment = deployment.options(
            autoscaling_config=autoscaling_config,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
        )
    # Declare the LLM stream-resume policy ON the deployment: handles
    # built from its config (serve.run's return, get_app_handle, and the
    # HTTP proxy's streaming path) migrate interrupted token streams onto
    # surviving replicas — HTTP clients survive drains/kills too, without
    # opting in per handle.
    deployment = deployment.options(stream_resume_fn=llm_stream_resume)
    if (
        engine_config is not None
        and engine_config.kv_fabric is not None
        and engine_config.kv_fabric.affinity
    ):
        # Prefix-affinity routing rides the same declared-on-deployment
        # path as stream resume: every handle built from the app's config
        # prefers the rendezvous replica for the prompt's leading
        # block-chain hash, so multi-turn sessions land where their KV
        # cache (device tier or fabric tier) already lives. Strictly a
        # tie-break — drain/exclusion/capacity still decide first.
        from ray_tpu.llm.kvfabric.affinity import LLMPrefixAffinity

        deployment = deployment.options(
            affinity_key_fn=LLMPrefixAffinity(engine_config.block_size)
        )
    return deployment.bind(
        engine_name, model_config, engine_config, params=params, seed=seed,
        draft_params=draft_params, engine_per_replica=engine_per_replica,
    )
