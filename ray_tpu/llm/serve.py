"""Serve integration: proxy → replica → engine actor → paged cache.

The ingress deployment is thin — replicas forward requests to one shared,
named `LLMServer` engine actor, so scaling HTTP replicas does not duplicate
model weights or split the continuous batch. Streaming responses ride the
actor streaming-generator path into Serve's ndjson/`stream=True` plumbing.
"""

from __future__ import annotations

import uuid
from typing import Optional

import ray_tpu
from ray_tpu import serve
from ray_tpu.llm.config import EngineConfig
from ray_tpu.llm.engine import LLMServer
from ray_tpu.models.gpt import GPTConfig


def get_or_create_engine_actor(
    engine_name: str = "default",
    model_config: Optional[GPTConfig] = None,
    engine_config: Optional[EngineConfig] = None,
    params=None,
    seed: int = 0,
    max_concurrency: int = 32,
):
    """Named engine actor shared by every ingress replica."""
    return (
        ray_tpu.remote(LLMServer)
        .options(
            name=f"llm_engine:{engine_name}",
            get_if_exists=True,
            max_concurrency=max_concurrency,
        )
        .remote(model_config, engine_config, params, seed)
    )


class LLMIngress:
    """Deployment callable: JSON dict in, generated token ids (or a token
    stream) out.

    Request schema: {"prompt_ids": [int, ...], "max_new_tokens": int?,
    "eos_id": int?, "stream": bool?}.
    """

    def __init__(
        self,
        engine_name: str = "default",
        model_config: Optional[GPTConfig] = None,
        engine_config: Optional[EngineConfig] = None,
        params=None,
        seed: int = 0,
    ):
        self._engine = get_or_create_engine_actor(
            engine_name, model_config, engine_config, params=params, seed=seed
        )

    def __call__(self, request: dict):
        if not isinstance(request, dict) or "prompt_ids" not in request:
            raise ValueError(
                'LLM requests must be {"prompt_ids": [...], ...}, got '
                f"{type(request).__name__}"
            )
        prompt_ids = request["prompt_ids"]
        max_new_tokens = request.get("max_new_tokens")
        eos_id = request.get("eos_id")
        if request.get("stream"):
            refs = self._engine.generate_stream.options(
                num_returns="streaming"
            ).remote(prompt_ids, max_new_tokens, eos_id)

            def token_stream():
                for ref in refs:
                    yield {"token_id": ray_tpu.get(ref)}

            return token_stream()
        return ray_tpu.get(
            self._engine.generate.remote(prompt_ids, max_new_tokens, eos_id)
        )

    def metrics(self) -> dict:
        return ray_tpu.get(self._engine.metrics.remote())

    def reset_prefix_cache(self) -> None:
        """Drop the engine's cached-but-unreferenced KV blocks (call after
        swapping served params, whose cached activations would be stale)."""
        ray_tpu.get(self._engine.reset_prefix_cache.remote())

    def check_health(self) -> bool:
        """Replica health forwards to the engine, but a busy engine (e.g.
        compiling a new bucket) must read as healthy — the controller's probe
        window is short and killing the replica would not unblock anything.
        Only a dead/raising engine fails the probe (the replacement replica
        then re-creates the named engine actor)."""
        from ray_tpu.exceptions import ActorError

        try:
            return bool(
                ray_tpu.get(self._engine.check_health.remote(), timeout=1.0)
            )
        except TimeoutError:
            return True
        except ActorError:
            return False


def build_app(
    model_config: Optional[GPTConfig] = None,
    engine_config: Optional[EngineConfig] = None,
    *,
    params=None,
    engine_name: Optional[str] = None,
    num_replicas: int = 1,
    max_concurrent_queries: int = 32,
    seed: int = 0,
) -> serve.Application:
    """Bind the LLM ingress for `serve.run` (HTTP via the existing proxy:
    POST /<app> with the request JSON). Pass trained weights via `params`;
    without them the engine serves a seed-initialized model.

    Each build_app call gets its own engine actor by default — the engine
    is keyed by `engine_name`, so two apps share one engine (one copy of
    the weights, one continuous batch) only when given the same explicit
    name. Never reuse a name across different model configs/params: the
    first creation wins and later apps would silently serve its weights."""
    if engine_name is None:
        engine_name = uuid.uuid4().hex[:8]
    deployment = serve.deployment(
        LLMIngress,
        name="LLMIngress",
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
    )
    return deployment.bind(
        engine_name, model_config, engine_config, params=params, seed=seed
    )
