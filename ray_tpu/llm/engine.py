"""LLM inference engine: continuous batching over the paged KV cache.

`LLMEngine` is the single-threaded core — one `step()` admits prefills,
feeds each in-flight prompt its next block-aligned chunk under the
per-step token budget (EngineConfig.max_prefill_tokens_per_step — long
prompts stream in over several steps instead of monopolizing one), runs
one iteration-level decode, streams tokens, and retires finished
sequences. `LLMServer` wraps it for actor use: a background step loop, a
blocking `generate`, and a `generate_stream` generator that pairs with
`.options(num_returns="streaming")` on the actor handle.

Observability (ray_tpu.util.metrics + util.tracing + llm.observability):
tokens/sec counters, decode batch occupancy, cache utilization, and queue
depth, plus — when EngineConfig.instrument is on — per-request lifecycle
spans (queue/prefill/decode/preempt, connected to the submitting task's
trace), TTFT / time-per-output-token / queue / e2e latency histograms, and
a flight-recorder ring of per-step records, all exported through the
standard Prometheus registry / tracing.traces() / flight_record().
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
import uuid
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from ray_tpu._private.fault_injection import maybe_fail
from ray_tpu.exceptions import EngineOverloadedError, PoisonRequestError
from ray_tpu.llm.cache import BlockAllocator, blocks_for_tokens
from ray_tpu.llm.config import EngineConfig
from ray_tpu.llm.model_runner import GPTRunner
from ray_tpu.llm.observability import (
    HOST_GAP_SECONDS_BOUNDARIES,
    PER_TOKEN_SECONDS_BOUNDARIES,
    REQUEST_SECONDS_BOUNDARIES,
    STEP_SECONDS_BOUNDARIES,
    FlightRecorder,
    RequestTrace,
)
from ray_tpu.llm.scheduler import (
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_EXPIRED,
    FINISH_LENGTH,
    Request,
    Scheduler,
    Sequence,
)
from ray_tpu.llm.spec import build_proposer
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Gauge, Histogram, get_or_create


class _InflightStep:
    """One dispatched-but-uncommitted decode step (async_scheduling).

    Holds everything the deferred commit needs: the batch exactly as it
    was dispatched (slot order matters — the chained token input is
    slot-aligned), the on-device `next_tokens` with its async host copy
    in flight, and the engine step index at dispatch time (failure
    attribution: a commit-time exception is pinned on the step that
    DISPATCHED the program, one step before it surfaces). `commit_idx`
    is the partial-commit resume pointer — after a poison dead-letter
    mid-commit, the retry resumes the loop exactly where it stopped.
    """

    __slots__ = (
        "seqs", "rids", "tokens_dev", "tokens_host",
        "dispatch_step", "commit_idx",
    )

    def __init__(self, seqs, rids, tokens_dev, dispatch_step):
        self.seqs: List[Sequence] = seqs
        self.rids: List[str] = rids
        self.tokens_dev = tokens_dev
        self.tokens_host: Optional[np.ndarray] = None
        self.dispatch_step = dispatch_step
        self.commit_idx = 0


class LLMEngine:
    """Not thread-safe; callers serialize access (LLMServer holds a lock)."""

    def __init__(
        self,
        model_config: Optional[GPTConfig] = None,
        engine_config: Optional[EngineConfig] = None,
        params=None,
        seed: int = 0,
        draft_params=None,
    ):
        self.model_config = model_config or GPTConfig()
        self.engine_config = engine_config or EngineConfig()
        if self.engine_config.draft_model_config is not None:
            # Fail fast with a message that names the DRAFT model before
            # any runner (and its device pools) is built: the draft mirror
            # pool shards on the same head axis as the target's, so both
            # head counts must divide the tp degree.
            from ray_tpu.ops.attention import validate_tp_heads

            validate_tp_heads(
                self.engine_config.draft_model_config.num_heads,
                self.engine_config.tensor_parallel_size,
                role="draft model",
            )
        self.runner = GPTRunner(
            self.model_config, self.engine_config, params=params, seed=seed
        )
        # Speculative decoding (ray_tpu.llm.spec): None when off. The
        # proposer only produces guesses; _run_verify scores them against
        # this engine's own model, so outputs never depend on it.
        self._spec = build_proposer(
            self.engine_config, seed=seed, draft_params=draft_params
        )
        self.allocator = BlockAllocator(
            self.engine_config.num_blocks,
            self.engine_config.block_size,
            enable_prefix_caching=self.engine_config.enable_prefix_caching,
            eviction_policy=self.engine_config.prefix_eviction_policy,
        )
        self.scheduler = Scheduler(
            self.allocator,
            self.engine_config.max_decode_slots,
            self.engine_config.max_blocks_per_seq,
        )
        # KV fabric (EngineConfig.kv_fabric): shared host-DRAM spill tier.
        # None keeps every hook cold — the allocator, scheduler, and step
        # loop behave bit-for-bit as before the fabric existed.
        self._fabric = None
        fcfg = self.engine_config.kv_fabric
        if fcfg is not None:
            block_bytes = self.runner.kv_block_bytes()
            if fcfg.byte_budget < block_bytes:
                raise ValueError(
                    f"kv_fabric.byte_budget ({fcfg.byte_budget} bytes) is "
                    f"smaller than one KV block ({block_bytes} bytes for "
                    "this model/engine config) — a fabric that cannot hold "
                    "a single block can never serve a hit; raise the "
                    "budget or drop the kv_fabric knob"
                )
            # Imported lazily: the kvfabric package's disagg module imports
            # this module, so a top-level import would cycle.
            from ray_tpu.llm.kvfabric.store import KVFabricClient

            self._fabric = KVFabricClient(
                fcfg.name,
                fcfg.byte_budget,
                rpc_timeout_s=fcfg.rpc_timeout_s,
                # A store RPC that exceeds its bound degrades to a miss AND
                # is counted distinctly (llm_engine_fabric_timeouts): a
                # hung store actor must never stall admission or eviction,
                # and an operator must be able to tell "store is slow"
                # from "store is cold". Bound method on a not-yet-finished
                # self is safe — the callback only fires on later RPCs.
                on_timeout=self._note_fabric_timeout,
            )
            # Spill on device eviction: demote a keyed block's content to
            # the host tier just before the allocator discards it.
            self.allocator.on_evict = self._spill_block
            # Admission extends the prefix match past the device cache.
            self.scheduler.fabric_probe = self._fabric.contains
        # A prefill-role engine's whole output is the KV blocks it
        # publishes: push every newly filled block eagerly, so the reply
        # to the caller is the barrier the decode-role admission needs.
        self._publish_on_fill = (
            self._fabric is not None
            and self.engine_config.engine_role == "prefill"
        )
        self._on_token: Dict[str, Callable[[int], None]] = {}
        self._on_finish: Dict[str, Callable[[Sequence], None]] = {}

        # Engines share one registered metric per name (several engines can
        # coexist in-process, one per Serve app); each engine is its own
        # series via the `engine` tag.
        self._metric_tags = {"engine": uuid.uuid4().hex[:8]}
        self._tokens_generated = get_or_create(
            Counter,
            "llm_engine_generated_tokens",
            "Tokens generated (prefill+decode)",
            tag_keys=("engine",),
        )
        self._preemptions = get_or_create(
            Counter,
            "llm_engine_preemptions",
            "Sequences preempted on cache pressure",
            tag_keys=("engine",),
        )
        self._occupancy = get_or_create(
            Gauge,
            "llm_engine_batch_occupancy",
            "Active decode slots / max_decode_slots, last step",
            tag_keys=("engine",),
        )
        self._cache_util = get_or_create(
            Gauge,
            "llm_engine_cache_utilization",
            "Allocated KV blocks / usable",
            tag_keys=("engine",),
        )
        self._queue_depth = get_or_create(
            Gauge,
            "llm_engine_queue_depth",
            "Requests waiting for a decode slot",
            tag_keys=("engine",),
        )
        self._prefix_hits = get_or_create(
            Counter,
            "llm_engine_prefix_cache_hit_tokens",
            "Prompt tokens served from the prefix cache instead of computed",
            tag_keys=("engine",),
        )
        self._prefix_hit_rate = get_or_create(
            Gauge,
            "llm_engine_prefix_cache_hit_rate",
            "Cumulative prefix-cache hit tokens / prefill tokens",
            tag_keys=("engine",),
        )
        self._evictable_blocks = get_or_create(
            Gauge,
            "llm_engine_evictable_blocks",
            "Cached-but-unreferenced KV blocks (reusable until evicted)",
            tag_keys=("engine",),
        )
        self._dead_letter_count = get_or_create(
            Counter,
            "llm_engine_dead_letter_requests",
            "Requests failed in isolation after poisoning an engine step",
            tag_keys=("engine",),
        )
        self._shed_count = get_or_create(
            Counter,
            "llm_engine_shed_requests",
            "Submissions rejected fast by bounded admission "
            "(max_queue_len / max_queue_tokens) or dead-on-arrival "
            "deadlines — typed overload sheds, not failures",
            tag_keys=("engine",),
        )
        self._expired_count = get_or_create(
            Counter,
            "llm_engine_expired_requests",
            "Admitted requests dropped at their end-to-end deadline "
            "(queued: before any prefill ran; decoding: aborted "
            "mid-stream with blocks reclaimed)",
            tag_keys=("engine",),
        )
        self._prefill_backlog = get_or_create(
            Gauge,
            "llm_engine_prefill_backlog_tokens",
            "Prompt tokens admitted or queued but not yet fed through a "
            "prefill program (chunked prefill drains this at "
            "max_prefill_tokens_per_step per engine step)",
            tag_keys=("engine",),
        )
        self._spec_proposed = get_or_create(
            Counter,
            "llm_engine_spec_proposed_tokens",
            "Speculative tokens scored by the verify program",
            tag_keys=("engine",),
        )
        self._spec_accepted = get_or_create(
            Counter,
            "llm_engine_spec_accepted_tokens",
            "Speculative tokens that matched the target argmax and were "
            "committed (excludes the always-emitted correction/bonus token)",
            tag_keys=("engine",),
        )
        self._spec_acceptance = get_or_create(
            Gauge,
            "llm_engine_spec_acceptance_rate",
            "Cumulative accepted / proposed speculative tokens",
            tag_keys=("engine",),
        )
        self._fabric_spills = get_or_create(
            Counter,
            "llm_engine_fabric_spill_blocks",
            "KV blocks demoted to the fabric host tier (eviction spill, "
            "prefill-role publication, drain flush)",
            tag_keys=("engine",),
        )
        self._fabric_restores = get_or_create(
            Counter,
            "llm_engine_fabric_restore_blocks",
            "KV blocks restored from the fabric into device slots",
            tag_keys=("engine",),
        )
        self._fabric_hits = get_or_create(
            Counter,
            "llm_engine_fabric_hit_blocks",
            "Admission-probe hits: blocks found in the fabric past the "
            "device prefix match",
            tag_keys=("engine",),
        )
        self._fabric_hit_rate = get_or_create(
            Gauge,
            "llm_engine_fabric_hit_rate",
            "Cumulative fabric-restored tokens / prefill tokens (the "
            "fabric's own share of the prefix-cache hit rate)",
            tag_keys=("engine",),
        )
        self._fabric_bytes_used = get_or_create(
            Gauge,
            "llm_engine_fabric_bytes_used",
            "Fabric store occupancy in bytes (the store is shared across "
            "engines on the fabric; refreshed on stats scrape)",
            tag_keys=("engine",),
        )
        self._fabric_timeouts = get_or_create(
            Counter,
            "llm_engine_fabric_timeouts",
            "Fabric store RPCs that exceeded kv_fabric.rpc_timeout_s and "
            "degraded to a miss/no-op (a hung store never stalls "
            "admission or eviction)",
            tag_keys=("engine",),
        )
        # Request-level latency histograms (the serving SLO trio + queue):
        # observed only at lifecycle boundaries, never per token.
        self._h_ttft = get_or_create(
            Histogram,
            "llm_request_ttft_seconds",
            "Submission to first generated token",
            boundaries=REQUEST_SECONDS_BOUNDARIES,
            tag_keys=("engine",),
        )
        self._h_tpot = get_or_create(
            Histogram,
            "llm_request_time_per_output_token_seconds",
            "Mean inter-token latency after the first token, per request",
            boundaries=PER_TOKEN_SECONDS_BOUNDARIES,
            tag_keys=("engine",),
        )
        self._h_queue = get_or_create(
            Histogram,
            "llm_request_queue_time_seconds",
            "Waiting-for-a-decode-slot time (one sample per admission, "
            "including preempt-resume re-admissions)",
            boundaries=REQUEST_SECONDS_BOUNDARIES,
            tag_keys=("engine",),
        )
        self._h_e2e = get_or_create(
            Histogram,
            "llm_request_e2e_seconds",
            "Submission to terminal state",
            boundaries=REQUEST_SECONDS_BOUNDARIES,
            tag_keys=("engine",),
        )
        self._h_step = get_or_create(
            Histogram,
            "llm_engine_step_seconds",
            "One engine phase dispatch (prefill per chunk per sequence, "
            "decode or speculative verify per batched step); chunk=cont "
            "marks a mid-prompt prefill chunk, chunk=final the dispatch "
            "that completes a prompt (n/a for decode/verify)",
            boundaries=STEP_SECONDS_BOUNDARIES,
            tag_keys=("engine", "phase", "attn_impl", "chunk"),
        )
        self._h_host_gap = get_or_create(
            Histogram,
            "llm_engine_step_host_gap_seconds",
            "Host time between consecutive decode/verify device "
            "dispatches: how long the previous step's results had been "
            "sitting on host before the next program was queued — the "
            "device's scheduling-induced idle window, and the number "
            "async_scheduling exists to shrink. A chained async dispatch "
            "issued BEFORE the previous step's results were fetched "
            "records 0.",
            boundaries=HOST_GAP_SECONDS_BOUNDARIES,
            tag_keys=("engine",),
        )
        # Which paged-attention implementation the runner resolved (pallas
        # fused kernel vs XLA reference): tagged onto the step histograms
        # and per-step flight records so the observability plane can
        # attribute a speedup (or regression) to the kernel in production.
        self._attn_impl = self.runner.attn_impl
        # How many chips this replica's mesh spans: stamped on stats() and
        # every flight-recorder step record so a fleet operator can tell a
        # tp=4 replica's step times from a single-chip one at a glance.
        self._tp = self.runner.tensor_parallel_size
        # Pre-merged tag dicts so the step loop never builds dicts. Full
        # prefill runs model.apply with no paged caches — the knob cannot
        # affect it — so its series is tagged "n/a" rather than letting
        # unrelated latency differences read as kernel effects; only the
        # partial-prefill and decode programs dispatch on attn_impl. The
        # chunk tag splits prefill dispatches into mid-prompt chunks
        # ("cont") vs the dispatch that completes a prompt ("final", which
        # is also every unchunked prefill); decode/verify never chunk.
        self._step_tags = {
            phase: {
                **self._metric_tags,
                "phase": phase,
                "attn_impl": (
                    "n/a" if phase == "prefill" else self._attn_impl
                ),
                "chunk": (
                    "n/a" if phase in ("decode", "verify") else "final"
                ),
            }
            for phase in ("prefill", "partial_prefill", "decode", "verify")
        }
        self._chunk_step_tags = {
            phase: {**self._step_tags[phase], "chunk": "cont"}
            for phase in ("prefill", "partial_prefill")
        }
        # Resolved once: None = chunking off (whole prompts in one
        # dispatch), else the per-step prompt-token budget.
        self._prefill_budget = self.engine_config.prefill_token_budget
        # Observability plane (EngineConfig.instrument): per-request phase
        # spans + the per-step flight-recorder ring. The recorder object
        # always exists (step FAILURES are recorded regardless), but
        # per-step records and spans are compiled out when instrument=False.
        self._instrument = self.engine_config.instrument
        self.flight_recorder = FlightRecorder(
            self.engine_config.flight_recorder_capacity
        )
        self._req_traces: Dict[str, RequestTrace] = {}
        if self._instrument or self._spec is not None:
            # Preemption must also drop a stateful proposer's per-request
            # resources (draft KV blocks) — the resume re-prefills both
            # caches — so the hook installs whenever either plane needs it.
            self.scheduler.on_preempt = self._note_preempt
        # Poison-request isolation: records of requests failed in isolation
        # after an attributable step exception, newest last.
        self._dead_letters: deque = deque(
            maxlen=self.engine_config.dead_letter_capacity
        )
        # Overload control plane. The shed ring mirrors the dead-letter
        # ring for bounded-admission rejections (shed_requests());
        # _deadline_count gates the per-step expiry sweep so an engine
        # that has never seen a deadline pays one int compare per step —
        # the default path stays bit-for-bit.
        self._sheds: deque = deque(maxlen=self.engine_config.shed_capacity)
        self._shed_total = 0
        self._expired_total = 0
        self._fabric_timeout_total = 0
        self._deadline_count = 0
        # Request whose per-sequence section of step() is currently running;
        # a step exception raised there is attributed to it.
        self._current_rid: Optional[str] = None
        self._steps = 0
        self._decode_tokens = 0
        self._decode_slot_steps = 0
        self._prefill_tokens = 0
        self._prefill_chunk_dispatches = 0  # prefill program dispatches
        self._chunked_prefill_requests = 0  # prompts that took > 1 chunk
        self._cache_hit_tokens = 0
        self._fabric_spilled_total = 0
        self._fabric_restored_total = 0
        self._fabric_hit_total = 0
        self._fabric_restored_tokens = 0
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0
        self._spec_emitted_total = 0
        self._verify_steps = 0
        # Async (double-buffered) step loop state. `_inflight` holds
        # dispatched-but-uncommitted decode records, oldest first; depth
        # is transiently 2 between a chained dispatch and the commit of
        # the record it chained from. Always empty with async off.
        self._async = self.engine_config.async_scheduling
        self._inflight: Deque[_InflightStep] = deque()
        # Dispatch index of the record being committed right now: a
        # commit-time failure is attributed one step late, against the
        # step that dispatched the failing program (failure_step()).
        self._attribution_step: Optional[int] = None
        # Host-gap apparatus (both loop modes): perf_counter stamp of the
        # moment the previous decode/verify results became host-readable,
        # the per-step gap/dispatch/commit fields the flight record
        # carries, and the cumulative aggregates stats() exposes.
        self._last_ready_t: Optional[float] = None
        self._step_gap: Optional[float] = None
        self._step_dispatch_wall: Optional[float] = None
        self._step_commits: List[dict] = []
        # Time-ledger stamps (instrument-gated, like the record they ride
        # in): wall time the decode/verify results became host-readable,
        # and measured seconds this step spent in prefill programs and in
        # fabric restore RPCs — the fleet ledger decomposes duration_s
        # into host-schedule / device / commit / prefill / fabric-wait
        # from exactly these fields (ray_tpu.observability.ledger).
        self._step_ready_wall: Optional[float] = None
        self._step_prefill_s = 0.0
        self._step_fabric_wait_s = 0.0
        self._host_gap_total = 0.0
        self._host_gap_count = 0
        self._host_gap_last: Optional[float] = None
        # Preallocated per-step decode/verify input buffers, zero-filled
        # and repopulated each dispatch instead of np.zeros-allocated
        # (the steady decode loop does no numpy allocation at all —
        # asserted by test). Safe to reuse: the sync runner blocks on the
        # program before the next fill, and the async runner converts
        # with a guaranteed copy at dispatch.
        slots = self.engine_config.max_decode_slots
        nb = self.engine_config.max_blocks_per_seq
        self._dec_tokens = np.zeros((slots,), np.int32)
        self._dec_positions = np.zeros((slots,), np.int32)
        self._dec_block_tables = np.zeros((slots, nb), np.int32)
        self._dec_context_lens = np.zeros((slots,), np.int32)
        self._verify_inputs = (
            {
                s: (
                    np.zeros((slots, s), np.int32),
                    np.zeros((slots, nb), np.int32),
                    np.zeros((slots,), np.int32),
                    np.zeros((slots,), np.int32),
                )
                for s in self.engine_config.verify_buckets()
            }
            if self._spec is not None
            else {}
        )
        self._start = time.monotonic()

    # ---------------- request lifecycle ----------------

    def add_request(
        self,
        prompt_ids: List[int],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
        request_id: Optional[str] = None,
        on_token: Optional[Callable[[int], None]] = None,
        on_finish: Optional[Callable[[Sequence], None]] = None,
        deadline_s: Optional[float] = None,
    ) -> str:
        ecfg = self.engine_config
        if max_new_tokens is None:
            max_new_tokens = ecfg.default_max_new_tokens
        if ecfg.engine_role == "prefill":
            # A prefill-role engine never decodes: the request finishes at
            # its first sampled token, after every full prompt block has
            # been published to the fabric for the decode-role engine.
            max_new_tokens = 1
        prompt_ids = [int(t) for t in prompt_ids]
        if not prompt_ids:
            raise ValueError("prompt_ids must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt_ids) + max_new_tokens
        if total > ecfg.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt_ids)}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds max_model_len "
                f"{ecfg.max_model_len}"
            )
        # A preempted sequence re-prefills prompt+generated (up to total-1
        # tokens), so the whole lifetime must fit the bucket table and the
        # block pool — otherwise the request could never be (re)admitted and
        # the engine would spin without progress.
        largest_bucket = ecfg.buckets()[-1]
        if total - 1 > largest_bucket:
            raise ValueError(
                f"prompt + max_new_tokens - 1 = {total - 1} exceeds the "
                f"largest prefill bucket {largest_bucket}; raise "
                "prefill_buckets or shorten the request"
            )
        need_blocks = blocks_for_tokens(total, ecfg.block_size)
        if need_blocks > self.allocator.num_usable:
            raise ValueError(
                f"request needs {need_blocks} cache blocks but the pool "
                f"only has {self.allocator.num_usable}; raise num_blocks"
            )
        request_id = request_id or uuid.uuid4().hex
        if self.scheduler.is_active(request_id):
            raise ValueError(f"request_id {request_id!r} is already active")
        if deadline_s is not None:
            # Dead-on-arrival: the deadline (monotonic, set at the client
            # boundary) passed in transit. Admitting it would spend a
            # prefill program on tokens no caller can use.
            now = time.monotonic()
            if now >= deadline_s:
                self._record_shed(request_id, "expired_at_submit", 0.0)
                raise TimeoutError(
                    f"request {request_id} arrived "
                    f"{now - deadline_s:.3f}s past its deadline"
                )
        cap_len = ecfg.max_queue_len
        cap_tok = ecfg.max_queue_tokens
        if cap_len is not None or cap_tok is not None:
            qlen = len(self.scheduler.waiting)
            reason = None
            if cap_len is not None and qlen >= cap_len:
                reason = f"queue_len {qlen} >= max_queue_len {cap_len}"
            elif cap_tok is not None:
                qtok = self.scheduler.prefill_backlog_tokens()
                if qtok + len(prompt_ids) > cap_tok:
                    reason = (
                        f"queued tokens {qtok} + prompt {len(prompt_ids)} "
                        f"> max_queue_tokens {cap_tok}"
                    )
            if reason is not None:
                # Rough drain hint, never a guarantee: one admission wave
                # (~max_prefills_per_step worth of steps) per queued
                # request ahead of the caller, capped so callers never
                # sleep longer than the router's own backoff ceiling.
                retry_after = min(
                    2.0, 0.05 * (1.0 + qlen / ecfg.max_decode_slots)
                )
                self._record_shed(request_id, reason, retry_after)
                raise EngineOverloadedError(
                    engine=self._metric_tags["engine"],
                    reason=reason,
                    queue_len=qlen,
                    retry_after_s=retry_after,
                )
        req = Request(
            request_id=request_id,
            prompt_ids=prompt_ids,
            max_new_tokens=max_new_tokens,
            eos_id=eos_id,
            deadline_s=deadline_s,
        )
        if on_token is not None:
            self._on_token[request_id] = on_token
        if on_finish is not None:
            self._on_finish[request_id] = on_finish
        self.scheduler.add(Sequence(req))
        if deadline_s is not None:
            self._deadline_count += 1
        if self._instrument:
            # Submission runs on the caller's thread (an actor-task context
            # when reached through LLMServer), so the ambient trace context
            # chains this request's lifecycle spans under the Serve
            # handle → replica → engine-actor task spans. The engine loop
            # thread later emits against the captured context explicitly.
            self._req_traces[request_id] = RequestTrace(
                request_id, tracing.capture_context()
            )
        return request_id

    def abort(self, request_id: str) -> bool:
        seq = self.scheduler.abort(request_id)
        if seq is not None:
            self._finished(seq)
            return True
        return False

    def has_work(self) -> bool:
        # An in-flight async record is work even when the scheduler is
        # empty (every member aborted mid-flight): one more step drains
        # it, so callers' step loops never strand a dispatched program.
        return self.scheduler.has_work() or bool(self._inflight)

    # ---------------- poison-request isolation ----------------

    def culprit_for(self, exc: BaseException) -> Optional[str]:
        """Which active request a step exception is attributable to: the
        exception's own request_id (PoisonRequestError and injected faults
        carry one) or the request whose per-sequence section of step() was
        running. None when the failure can't be pinned on one request."""
        rid = getattr(exc, "request_id", None) or self._current_rid
        if rid and self.scheduler.is_active(rid):
            return rid
        return None

    def failure_step(self) -> int:
        """Step index a failure surfacing NOW should be attributed to.
        Under async_scheduling a decode program's commit runs one step
        after its dispatch, so an exception raised inside the commit loop
        belongs to the in-flight record's DISPATCH index (where the
        failing program and its batch actually ran) — not the current
        step counter. Outside a commit this is simply the current step."""
        if self._attribution_step is not None:
            return self._attribution_step
        return self._steps

    def fail_request(self, request_id: str, exc: BaseException) -> bool:
        """Fail one request in isolation: release its KV blocks, record a
        dead letter, and fire its finish callback (finish_reason="error").
        Returns False when the request is not active."""
        seq = self.scheduler.abort(request_id)
        if seq is None:
            return False
        seq.finish_reason = FINISH_ERROR
        prompt = seq.request.prompt_ids
        self._dead_letters.append(
            {
                "request_id": request_id,
                "prompt_hash": hashlib.sha1(
                    ",".join(map(str, prompt)).encode()
                ).hexdigest()[:16],
                "prompt_len": len(prompt),
                "tokens_generated": len(seq.generated),
                "error": repr(exc),
                "step": self.failure_step(),
                "time": time.time(),
            }
        )
        self._dead_letter_count.inc(tags=self._metric_tags)
        rt = self._req_traces.get(request_id)
        if rt is not None:
            # The request span closes with error status + the step
            # exception that killed it (dead-letter attribution).
            rt.error = repr(exc)
        self._finished(seq)
        return True

    def dead_letters(self) -> List[dict]:
        """Records of requests failed in isolation, oldest first (bounded
        by EngineConfig.dead_letter_capacity)."""
        return list(self._dead_letters)

    # ---------------- overload control ----------------

    def _record_shed(
        self, request_id: Optional[str], reason: str, retry_after_s: float
    ) -> None:
        """One rejected submission: ring entry (shed_requests()), counter,
        and a flight-recorder shed record — every rejection leaves the
        same three traces a dead letter does, so overload is auditable
        after the fact, not just observable live."""
        qlen = len(self.scheduler.waiting)
        self._sheds.append(
            {
                "request_id": request_id,
                "reason": reason,
                "queue_len": qlen,
                "retry_after_s": retry_after_s,
                "step": self._steps,
                "time": time.time(),
            }
        )
        self._shed_total += 1
        self._shed_count.inc(tags=self._metric_tags)
        self.flight_recorder.record_shed(
            request_id, reason, qlen, self._steps
        )

    def shed_requests(self) -> List[dict]:
        """Records of submissions rejected by bounded admission (or dead
        on arrival), oldest first (bounded by EngineConfig.shed_capacity)
        — the dead_letters() analogue for the overload plane."""
        return list(self._sheds)

    def _note_fabric_timeout(self) -> None:
        """KVFabricClient on_timeout hook: one store RPC exceeded its
        bound and degraded to a miss/no-op."""
        self._fabric_timeout_total += 1
        self._fabric_timeouts.inc(tags=self._metric_tags)

    def _expire_deadlines(self) -> None:
        """Per-step deadline enforcement (monotonic clock, matching
        Request.deadline_s — never wall time, which steps under NTP).
        Runs at the top of both step loops, so a queued request whose
        deadline passed is dropped BEFORE schedule_prefills can feed it
        to a prefill program, and a decoding one goes through the normal
        finish teardown — KV blocks, draft-mirror blocks, and any
        lookahead reservation reclaimed within this step. Under
        async_scheduling the sweep precedes the chain attempt: an expiry
        is a batch-composition change, so the pipeline flushes and
        _commit_head's inactive-skip drops the in-flight orphan token.
        Engines that have never seen a deadline pay one int compare."""
        if not self._deadline_count:
            return
        now = time.monotonic()
        for seq in self.scheduler.expire_waiting(now):
            self._record_expiry(seq, "queued")
            self._finished(seq)
        for seq in self.scheduler.expired_running(now):
            self.scheduler.finish(seq, FINISH_EXPIRED)
            self._record_expiry(seq, "running")
            self._finished(seq)

    def _record_expiry(self, seq: Sequence, phase: str) -> None:
        self._expired_total += 1
        self._expired_count.inc(tags=self._metric_tags)
        rt = self._req_traces.get(seq.request.request_id)
        if rt is not None:
            # The request span closes with error status: an expiry is a
            # terminal deadline miss, not a clean finish.
            rt.error = "deadline expired"
        self.flight_recorder.record_expiry(
            seq.request.request_id, phase, self._steps, len(seq.generated)
        )

    def close_traces(self, exc: BaseException) -> None:
        """Close every in-flight request's trace with error status. The
        wedge and shutdown broadcasts end requests WITHOUT _finished()
        running, which would otherwise strand their emitted phase spans
        under a root span that never gets written — exactly during the
        incident the trace exists to explain."""
        now = time.time()
        error = repr(exc)
        for rid, rt in list(self._req_traces.items()):
            rt.error = error
            seq = self.scheduler._active.get(rid)
            if seq is not None:
                rt.on_finish(now, seq)
        self._req_traces.clear()

    # ---------------- stepping ----------------

    def step(self) -> dict:
        """One engine iteration: admit prefills, feed each in-flight
        prompt its next chunk under the per-step token budget, decode
        every decode-ready sequence one token, emit tokens, retire
        finished sequences. A sequence mid-chunk stays `prefilling` — it
        never enters the decode batch, so a chunk failure (or a step
        retry) simply re-plans from its committed num_cached; no requeue
        is needed to keep the running set consistent."""
        if self._async:
            return self._step_async()
        ecfg = self.engine_config
        preempted_before = self.scheduler.num_preemptions
        step_hit_tokens = 0
        self._current_rid = None
        maybe_fail("llm.step")
        instrument = self._instrument
        # Wall clock for record identity ("time" field), perf_counter for
        # the duration — wall time steps under NTP and would corrupt
        # duration_s exactly when an operator is staring at the recorder.
        t_step = time.time() if instrument else 0.0
        t_step_p = time.perf_counter() if instrument else 0.0
        bytes_before = self._host_transfer_bytes() if instrument else 0
        self._step_gap = None
        self._step_dispatch_wall = None
        self._step_commits = []
        self._step_ready_wall = None
        self._step_prefill_s = 0.0
        self._step_fabric_wait_s = 0.0

        # Deadline sweep BEFORE admission: a queued request whose deadline
        # passed must never reach schedule_prefills (resource-true expiry).
        self._expire_deadlines()
        admitted = self.scheduler.schedule_prefills(
            ecfg.max_prefills_per_step
        )
        # KV-fabric restores commit BETWEEN admission and chunk planning:
        # each committed block advances its sequence's num_cached, so the
        # chunk plan below (and the first chunk's hit-token accounting,
        # which reads the offset) already sees the restored prefix.
        step_restored = 0
        if self._fabric is not None:
            step_restored = self._apply_fabric_restores(admitted)
        # Mixed-step dispatch: this step's chunk plan spans newly admitted
        # prompts AND prompts already mid-prefill from earlier steps,
        # oldest first, capped by the token budget (None = whole prompts,
        # the pre-chunking behavior).
        plans = self.scheduler.schedule_prefill_chunks(self._prefill_budget)
        prefill_info: List[dict] = []
        step_hit_tokens += self._run_prefill_chunks(plans, prefill_info)

        decoding = self.scheduler.schedule_decode()
        spec_info: Optional[dict] = None
        if decoding:
            if self._spec is not None:
                spec_info = self._run_verify(decoding)
            if spec_info is None:
                # Speculation off, or no sequence had proposals this step:
                # the plain decode program is already compiled and exactly
                # equivalent for one fed token per slot.
                self._run_decode(decoding)
        else:
            # No decode this step: the next dispatch follows an idle
            # stretch, not host scheduling work — don't count it as gap.
            self._last_ready_t = None

        self._steps += 1
        # A stepping engine exports its whole metric family: counters and
        # histograms that happen not to fire after a registry reset (test
        # isolation) must still re-register, or their series vanish from
        # the exposition. One int compare each — nothing on the token path.
        family = (
            self._preemptions, self._prefix_hits, self._tokens_generated,
            self._dead_letter_count, self._shed_count, self._expired_count,
            self._h_ttft, self._h_tpot,
            self._h_queue, self._h_e2e, self._h_step, self._h_host_gap,
        )
        if self._spec is not None:
            family = family + (
                self._spec_proposed, self._spec_accepted,
                self._spec_acceptance,
            )
        if self._fabric is not None:
            family = family + (
                self._fabric_spills, self._fabric_restores,
                self._fabric_hits, self._fabric_hit_rate,
                self._fabric_bytes_used, self._fabric_timeouts,
            )
        for metric in family:
            metric._ensure_registered()
        preempted = self.scheduler.num_preemptions - preempted_before
        if preempted:
            self._preemptions.inc(preempted, tags=self._metric_tags)
        if step_hit_tokens:
            self._cache_hit_tokens += step_hit_tokens
            self._prefix_hits.inc(step_hit_tokens, tags=self._metric_tags)
        occupancy = len(decoding) / ecfg.max_decode_slots
        self._occupancy.set(occupancy, tags=self._metric_tags)
        self._cache_util.set(self.allocator.utilization(), tags=self._metric_tags)
        self._queue_depth.set(len(self.scheduler.waiting), tags=self._metric_tags)
        self._prefix_hit_rate.set(
            self._cache_hit_tokens / max(self._prefill_tokens, 1),
            tags=self._metric_tags,
        )
        self._evictable_blocks.set(
            self.allocator.num_evictable, tags=self._metric_tags
        )
        if self._fabric is not None:
            self._fabric_hit_rate.set(
                self._fabric_restored_tokens / max(self._prefill_tokens, 1),
                tags=self._metric_tags,
            )
        backlog = self.scheduler.prefill_backlog_tokens()
        self._prefill_backlog.set(backlog, tags=self._metric_tags)
        if instrument:
            decode_label = "verify" if spec_info is not None else "decode"
            phase = "+".join(
                p
                for p, on in (("prefill", plans), (decode_label, decoding))
                if on
            ) or "idle"
            record = {
                "step": self._steps - 1,
                "phase": phase,
                "attn_impl": self._attn_impl,
                "tensor_parallel_size": self._tp,
                # Explicit host<->device bytes this step moved (program
                # inputs + sampled tokens, target AND draft runner):
                # flat in tensor_parallel_size — the tp acceptance tests
                # assert the series is identical at tp=1 and tp=2, i.e.
                # no per-token gather hides in the decode loop.
                "host_transfer_bytes": (
                    self._host_transfer_bytes() - bytes_before
                ),
                "batch_size": len(decoding),
                "num_prefills": len(plans),
                "prefills": prefill_info,
                # Acceptance invariant: with chunking on, tokens_in (the
                # prompt tokens actually fed this step) never exceeds
                # prefill_budget — asserted from these records in tests.
                "tokens_in": sum(p["tokens"] for p in prefill_info),
                "prefill_budget": self._prefill_budget,
                "prefill_backlog_tokens": backlog,
                "tokens_out": sum(1 for p in prefill_info if p["final"])
                + (
                    spec_info["emitted"]
                    if spec_info is not None
                    else len(decoding)
                ),
                "cache_hit_tokens": step_hit_tokens,
                "preempted": preempted,
                "queue_depth": len(self.scheduler.waiting),
                "duration_s": round(time.perf_counter() - t_step_p, 6),
                "time": t_step,
                # Dispatch/commit apparatus (sync loop: both halves run
                # in this step, so commits reference this step's own
                # dispatch index; host_gap_s is the device idle window
                # the async loop exists to shrink).
                "dispatch_time": self._step_dispatch_wall,
                "commits": self._step_commits,
                "host_gap_s": self._step_gap,
                # Ledger inputs: wall time the decode/verify results were
                # host-readable, measured prefill-plan seconds, measured
                # fabric-restore seconds (observability.ledger decomposes
                # duration_s into its time columns from these).
                "ready_time": self._step_ready_wall,
                "prefill_s": round(self._step_prefill_s, 6),
                "fabric_wait_s": round(self._step_fabric_wait_s, 6),
            }
            if spec_info is not None:
                # Verify record: which proposer ran, how wide the fed
                # bucket was, and the proposed/accepted/emitted counts —
                # the per-step acceptance story for the flight recorder.
                record["speculation"] = spec_info
            if self._fabric is not None:
                record["fabric_restored_blocks"] = step_restored
            self.flight_recorder.record_step(record)
        return {
            "num_prefilled": len(plans),
            "num_decoding": len(decoding),
            "occupancy": occupancy,
            "cache_utilization": self.allocator.utilization(),
            "queue_depth": len(self.scheduler.waiting),
            "preempted": preempted,
            "cache_hit_tokens": step_hit_tokens,
            "evictable_blocks": self.allocator.num_evictable,
            "prefill_backlog_tokens": backlog,
        }

    def _host_transfer_bytes(self) -> int:
        """Cumulative explicit host<->device bytes across the target
        runner AND the draft-model runner (whose mirror pool shards the
        same way): the per-step delta rides the flight records."""
        total = self.runner.host_transfer_bytes()
        spec_runner = (
            getattr(self._spec, "runner", None)
            if self._spec is not None
            else None
        )
        if spec_runner is not None:
            total += spec_runner.host_transfer_bytes()
        return total

    # ---------------- KV fabric ----------------

    def _apply_fabric_restores(self, admitted: List[Sequence]) -> int:
        """Resolve each newly admitted sequence's fabric restore plan
        (Scheduler._admit probed the fabric and pre-allocated the target
        slots): fetch the planned chain of payloads in one batch RPC and
        commit them in chain order — copy the content into the slot FIRST,
        then advance num_cached and register the chain key, so a
        half-written block is never discoverable under its key. The chain
        stops at the first miss or failed copy-in; the remaining slots
        simply stay plain prefill targets (no rollback needed — they are
        already legitimate mid-chain members of the block table, and
        num_cached never claimed them). Returns blocks restored."""
        bs = self.engine_config.block_size
        restored = 0
        hit_blocks = 0
        t_fabric = time.perf_counter() if self._instrument else 0.0
        for seq in admitted:
            plan = seq.pending_restore
            if not plan:
                continue
            seq.pending_restore = []
            self._current_rid = seq.request.request_id
            hit_blocks += len(plan)
            payloads = self._fabric.get_many([h for _, h in plan])
            for (block, h), payload in zip(plan, payloads):
                if payload is None:
                    break  # chain broken: later blocks cannot commit either
                try:
                    self.runner.restore_block(block, payload)
                except Exception:
                    break  # failed copy-in: the slot stays a prefill target
                seq.num_cached += bs
                seq.block_hashes.append(h)
                self.allocator.register(block, h)
                restored += 1
                self._fabric_restored_tokens += bs
        self._current_rid = None
        if hit_blocks:
            self._fabric_hit_total += hit_blocks
            self._fabric_hits.inc(hit_blocks, tags=self._metric_tags)
        if restored:
            self._fabric_restored_total += restored
            self._fabric_restores.inc(restored, tags=self._metric_tags)
        if self._instrument:
            # Wall this step spent blocked on fabric store RPCs + block
            # copy-ins: the ledger's fabric-wait column.
            self._step_fabric_wait_s = time.perf_counter() - t_fabric
        return restored

    def _spill_block(self, block: int, block_hash: int) -> None:
        """BlockAllocator.on_evict hook: demote the dying block's device
        content to the fabric's host tier, keyed by its chain hash. Best
        effort end to end — the allocator contains hook exceptions and
        the client degrades to a no-op — so eviction always completes."""
        if self._fabric.put(block_hash, self.runner.extract_block(block)):
            self._fabric_spilled_total += 1
            self._fabric_spills.inc(tags=self._metric_tags)

    def flush_kv_fabric(self) -> int:
        """Demote every cached-but-unreferenced device block into the
        fabric in one batch RPC — the drain path's cache preservation:
        a victim replica's reusable prefixes survive as fabric entries
        instead of dying with the engine actor. Returns how many of the
        flushed blocks are resident afterwards; 0 without a fabric."""
        if self._fabric is None:
            return 0
        items = [
            (h, self.runner.extract_block(block))
            for block, h in self.allocator.evictable_items()
        ]
        n = self._fabric.put_many(items)
        if n:
            self._fabric_spilled_total += n
            self._fabric_spills.inc(n, tags=self._metric_tags)
        return n

    def _run_decode(self, decoding: List[Sequence]) -> None:
        """One iteration-level decode dispatch: every running sequence
        advances exactly one token through the batched decode program."""
        ecfg = self.engine_config
        instrument = self._instrument
        t_decode = time.perf_counter() if instrument else 0.0
        # Preallocated input buffers: zero-fill + repopulate, never
        # allocate. Reuse is safe here because runner.decode blocks on
        # the program's results before this step returns.
        tokens = self._dec_tokens
        positions = self._dec_positions
        block_tables = self._dec_block_tables
        context_lens = self._dec_context_lens
        tokens.fill(0)
        positions.fill(0)
        block_tables.fill(0)
        context_lens.fill(0)
        for i, seq in enumerate(decoding):
            tokens[i] = seq.last_token
            positions[i] = seq.num_cached
            block_tables[i, : len(seq.block_table)] = seq.block_table
            context_lens[i] = seq.num_cached
        self._note_dispatch(pipelined=False)
        next_tokens = self.runner.decode(
            tokens, positions, block_tables, context_lens
        )
        # decode() returned == the program ran and its tokens are on
        # host: everything until the next dispatch is host-side gap.
        self._last_ready_t = time.perf_counter()
        if instrument:
            self._step_ready_wall = time.time()
        for i, seq in enumerate(decoding):
            # Per-sequence section; placed before any mutation so a
            # failure here leaves this sequence (and every later one,
            # whose decode simply re-runs from unchanged state next
            # step) consistent.
            self._current_rid = seq.request.request_id
            maybe_fail("llm.decode.seq", detail=seq.request.request_id)
            seq.num_cached += 1
            seq.generated.append(int(next_tokens[i]))
            if seq.num_cached % ecfg.block_size == 0:
                # A block just filled: publish it to the prefix cache
                # before a finish below could release it.
                self.scheduler.note_filled_blocks(seq)
            self._emit(seq)
            self._maybe_finish(seq)
        self._current_rid = None
        self._decode_tokens += len(decoding)
        self._decode_slot_steps += ecfg.max_decode_slots
        self._step_commits.append(
            {
                "dispatch_step": self._steps,
                "time": time.time(),
                "tokens": len(decoding),
                # Measured commit seconds (results host-readable -> all
                # emissions done): the ledger's commit column.
                "commit_s": round(
                    time.perf_counter() - self._last_ready_t, 6
                ),
            }
        )
        if instrument:
            # One observation per batched decode dispatch, never per
            # token — the whole emission loop rides in it.
            self._h_step.observe(
                time.perf_counter() - t_decode,
                tags=self._step_tags["decode"],
            )

    def _run_verify(self, decoding: List[Sequence]) -> Optional[dict]:
        """Speculative verify phase: ask the proposer for up to k tokens
        per running sequence, score them all in ONE target-model step
        (GPTRunner.verify — the partial-prefill shape batched over the
        decode slots), accept each sequence's longest proposal prefix that
        agrees with the target argmax plus the correction/bonus token, and
        roll back the rejected tail (Scheduler.rollback: context-length
        rewind + block-table trim). Emits 1..k+1 tokens per sequence per
        step; greedy outputs are token-identical to the plain decode loop
        by construction (out[i] IS the token decode would have produced).

        Returns the flight-recorder speculation record, or None when no
        sequence had usable proposals this step — the caller then runs the
        plain (already-compiled) decode program, which is exactly
        equivalent for one fed token per slot."""
        ecfg = self.engine_config
        instrument = self._instrument
        # Clock starts before the proposer: proposal cost (draft-model
        # steps, host-side matching) is part of what the verify phase
        # must amortize, so it belongs in the phase=verify histogram.
        t_verify = time.perf_counter() if instrument else 0.0
        k = ecfg.num_speculative_tokens
        proposals = self._spec.propose(decoding, k)
        plans: List[List[int]] = []
        max_fed = 1
        for seq, props in zip(decoding, proposals):
            props = [int(t) for t in props[:k]]
            # Never speculate past the request budget (the bonus token
            # must still fit) or the cache capacity; blocks are reserved
            # opportunistically — speculation never preempts a neighbor.
            cap = min(
                len(props),
                seq.request.max_new_tokens - len(seq.generated) - 1,
                ecfg.max_model_len - seq.num_cached - 1,
            )
            props = props[: max(cap, 0)]
            if props:
                props = props[
                    : self.scheduler.reserve_speculative(seq, len(props))
                ]
            plans.append(props)
            max_fed = max(max_fed, 1 + len(props))
        if max_fed == 1:
            return None
        s_bucket = ecfg.verify_bucket_for(max_fed)
        # Preallocated per-bucket input buffers (zero-fill + repopulate);
        # reuse is safe — runner.verify blocks on the program's results.
        tokens, block_tables, context_lens, true_lens = self._verify_inputs[
            s_bucket
        ]
        tokens.fill(0)
        block_tables.fill(0)
        context_lens.fill(0)
        true_lens.fill(0)
        for i, (seq, props) in enumerate(zip(decoding, plans)):
            tokens[i, 0] = seq.last_token
            if props:
                tokens[i, 1 : 1 + len(props)] = props
            block_tables[i, : len(seq.block_table)] = seq.block_table
            context_lens[i] = seq.num_cached
            true_lens[i] = 1 + len(props)
        self._note_dispatch(pipelined=False)
        out = self.runner.verify(
            tokens, block_tables, context_lens, true_lens
        )
        self._last_ready_t = time.perf_counter()
        if instrument:
            self._step_ready_wall = time.time()
        proposed = accepted = emitted = 0
        for i, (seq, props) in enumerate(zip(decoding, plans)):
            # Per-sequence commit section; nothing mutates before the
            # injection point, so a poisoned request dead-letters alone
            # and an unattributable failure retries the whole step from
            # consistent state (propose() is deterministic on retry).
            rid = seq.request.request_id
            self._current_rid = rid
            maybe_fail("engine.verify", detail=rid)
            base = seq.num_cached
            n_ok = 0
            while n_ok < len(props) and int(out[i, n_ok]) == props[n_ok]:
                n_ok += 1
            # out[i, n_ok] is the correction after a mismatch, or the
            # bonus token when every proposal matched — either way the
            # target's own argmax, so it is always committed.
            new_tokens = props[:n_ok] + [int(out[i, n_ok])]
            eos_id = seq.request.eos_id
            if eos_id is not None and eos_id in new_tokens:
                new_tokens = new_tokens[: new_tokens.index(eos_id) + 1]
            self.scheduler.rollback(seq, base + len(new_tokens))
            seq.generated.extend(new_tokens)
            self.scheduler.note_filled_blocks(seq)
            proposed += len(props)
            # Accepted = proposed tokens actually COMMITTED: an eos inside
            # the matched prefix truncates the commit, and the counter
            # must not claim the dropped tail.
            accepted += min(n_ok, len(new_tokens))
            emitted += len(new_tokens)
            self._emit(seq)
            self._maybe_finish(seq)
        self._current_rid = None
        self._decode_tokens += emitted
        self._decode_slot_steps += ecfg.max_decode_slots
        self._step_commits.append(
            {
                "dispatch_step": self._steps,
                "time": time.time(),
                "tokens": emitted,
                "commit_s": round(
                    time.perf_counter() - self._last_ready_t, 6
                ),
            }
        )
        self._verify_steps += 1
        self._spec_proposed_total += proposed
        self._spec_accepted_total += accepted
        self._spec_emitted_total += emitted
        if proposed:
            self._spec_proposed.inc(proposed, tags=self._metric_tags)
        if accepted:
            self._spec_accepted.inc(accepted, tags=self._metric_tags)
        self._spec_acceptance.set(
            self._spec_accepted_total / max(self._spec_proposed_total, 1),
            tags=self._metric_tags,
        )
        if instrument:
            # One observation per batched verify dispatch (proposer +
            # program + the whole commit loop), never per token.
            self._h_step.observe(
                time.perf_counter() - t_verify,
                tags=self._step_tags["verify"],
            )
        return {
            "mode": self._spec.name,
            "fed_bucket": s_bucket,
            "proposed": proposed,
            "accepted": accepted,
            "emitted": emitted,
        }

    # ---------------- async (double-buffered) stepping ----------------

    def _note_dispatch(self, pipelined: bool) -> None:
        """Host-gap sample at a decode/verify device dispatch: how long
        the previous step's results had been host-readable before this
        program was queued — the device idle window host scheduling
        opened. A chained async dispatch is issued BEFORE the previous
        step's results are even fetched, so it records exactly 0 (the
        gap definition's clamp: the dispatch beat the fetch)."""
        self._step_dispatch_wall = time.time()
        if pipelined:
            gap = 0.0
        else:
            if self._last_ready_t is None:
                return  # first dispatch / post-idle: no previous step
            gap = max(0.0, time.perf_counter() - self._last_ready_t)
        self._step_gap = gap
        self._host_gap_total += gap
        self._host_gap_count += 1
        self._host_gap_last = gap
        self._h_host_gap.observe(gap, tags=self._metric_tags)

    def _step_async(self) -> dict:
        """One iteration of the async step loop (EngineConfig.
        async_scheduling): decode splits into a dispatch phase and a
        deferred commit phase, pipelined one step deep.

        Steady state CHAINS: the in-flight decode's on-device
        `next_tokens` feed the next dispatch directly (positions and
        context_lens advance +1 — deterministic, value-free), THEN the
        in-flight step's values are fetched and committed one step
        behind, so the device is already running step N+1 while the host
        emits step N's tokens and plans admissions. Everything
        value-dependent is a pipeline-flush boundary (commit everything,
        then schedule normally): speculation (the proposer reads
        committed token history), any batch-composition change (finish /
        abort / preemption / a prompt joining — the chained token input
        is slot-aligned), block pressure the lookahead cannot cover
        without preempting (preemption must never run under an in-flight
        write), and a partially committed record left by a poison retry.

        Finishes are detected one step late, at commit: a chained
        dispatch may decode one token PAST a sequence's EOS/length stop.
        That overshoot token lands in the null block or a lookahead block
        freed with the sequence, is skipped at its record's commit, and
        never reaches a client. Greedy outputs are token-identical to the
        sync loop across every feature knob."""
        ecfg = self.engine_config
        preempted_before = self.scheduler.num_preemptions
        step_hit_tokens = 0
        self._current_rid = None
        maybe_fail("llm.step")
        instrument = self._instrument
        t_step = time.time() if instrument else 0.0
        t_step_p = time.perf_counter() if instrument else 0.0
        bytes_before = self._host_transfer_bytes() if instrument else 0
        self._step_gap = None
        self._step_dispatch_wall = None
        self._step_commits = []
        self._step_ready_wall = None
        self._step_prefill_s = 0.0
        self._step_fabric_wait_s = 0.0

        # Deadline sweep before the chain attempt: an expiry changes the
        # batch composition, so _try_chain refuses and the pipeline
        # flushes — the expired sequence's in-flight token is dropped by
        # _commit_head's inactive-skip, never emitted.
        self._expire_deadlines()
        # Chained dispatch FIRST — before any commit, admission, or
        # metric work: the whole point is that the device gets its next
        # program while the host still owes this step's bookkeeping. A
        # record mid-partial-commit (poison retry) or a second in-flight
        # record never chains; both flush below.
        chained_seqs: Optional[List[Sequence]] = None
        if (
            self._spec is None
            and len(self._inflight) == 1
            and self._inflight[0].commit_idx == 0
        ):
            chained_seqs = self._try_chain(self._inflight[0])
        if chained_seqs is not None:
            # Commit the record the chain fed from (its async host copy
            # has been in flight since its dispatch); the chained record
            # stays in flight for the next iteration.
            self._commit_head()
        else:
            # Flush boundary: commit everything in dispatch order, then
            # schedule normally from fully committed state.
            while self._inflight:
                self._commit_head()

        admitted = self.scheduler.schedule_prefills(
            ecfg.max_prefills_per_step
        )
        step_restored = 0
        if self._fabric is not None:
            step_restored = self._apply_fabric_restores(admitted)
        plans = self.scheduler.schedule_prefill_chunks(self._prefill_budget)
        prefill_info: List[dict] = []
        step_hit_tokens += self._run_prefill_chunks(plans, prefill_info)

        spec_info: Optional[dict] = None
        dispatched = chained_seqs is not None
        if chained_seqs is not None:
            decoding = chained_seqs
        else:
            decoding = self.scheduler.schedule_decode()
            if decoding:
                if self._spec is not None:
                    # Speculation composes as flush-every-step: acceptance
                    # is value-dependent, so the verify path runs the sync
                    # dispatch+commit inline (still token-identical).
                    spec_info = self._run_verify(decoding)
                    if spec_info is None:
                        self._run_decode(decoding)
                else:
                    self._dispatch_decode_async(decoding)
                    dispatched = True
            else:
                self._last_ready_t = None

        self._steps += 1
        family = (
            self._preemptions, self._prefix_hits, self._tokens_generated,
            self._dead_letter_count, self._shed_count, self._expired_count,
            self._h_ttft, self._h_tpot,
            self._h_queue, self._h_e2e, self._h_step, self._h_host_gap,
        )
        if self._spec is not None:
            family = family + (
                self._spec_proposed, self._spec_accepted,
                self._spec_acceptance,
            )
        if self._fabric is not None:
            family = family + (
                self._fabric_spills, self._fabric_restores,
                self._fabric_hits, self._fabric_hit_rate,
                self._fabric_bytes_used, self._fabric_timeouts,
            )
        for metric in family:
            metric._ensure_registered()
        preempted = self.scheduler.num_preemptions - preempted_before
        if preempted:
            self._preemptions.inc(preempted, tags=self._metric_tags)
        if step_hit_tokens:
            self._cache_hit_tokens += step_hit_tokens
            self._prefix_hits.inc(step_hit_tokens, tags=self._metric_tags)
        occupancy = len(decoding) / ecfg.max_decode_slots
        self._occupancy.set(occupancy, tags=self._metric_tags)
        self._cache_util.set(
            self.allocator.utilization(), tags=self._metric_tags
        )
        self._queue_depth.set(
            len(self.scheduler.waiting), tags=self._metric_tags
        )
        self._prefix_hit_rate.set(
            self._cache_hit_tokens / max(self._prefill_tokens, 1),
            tags=self._metric_tags,
        )
        self._evictable_blocks.set(
            self.allocator.num_evictable, tags=self._metric_tags
        )
        if self._fabric is not None:
            self._fabric_hit_rate.set(
                self._fabric_restored_tokens / max(self._prefill_tokens, 1),
                tags=self._metric_tags,
            )
        backlog = self.scheduler.prefill_backlog_tokens()
        self._prefill_backlog.set(backlog, tags=self._metric_tags)
        committed_tokens = sum(c["tokens"] for c in self._step_commits)
        if instrument:
            decode_label = "verify" if spec_info is not None else "decode"
            parts = []
            if plans:
                parts.append("prefill")
            if spec_info is not None or (decoding and not dispatched):
                parts.append(decode_label)
            elif dispatched:
                parts.append("decode")
            elif self._step_commits:
                # Drain-only iteration: nothing dispatched, but a stale
                # in-flight record committed (e.g. every member finished
                # or aborted since its dispatch).
                parts.append("commit")
            phase = "+".join(parts) or "idle"
            record = {
                "step": self._steps - 1,
                "loop": "async",
                "phase": phase,
                "attn_impl": self._attn_impl,
                "tensor_parallel_size": self._tp,
                "host_transfer_bytes": (
                    self._host_transfer_bytes() - bytes_before
                ),
                "batch_size": len(decoding),
                "num_prefills": len(plans),
                "prefills": prefill_info,
                "tokens_in": sum(p["tokens"] for p in prefill_info),
                "prefill_budget": self._prefill_budget,
                "prefill_backlog_tokens": backlog,
                # Async semantics: tokens_out counts tokens COMMITTED
                # this iteration (prefill finals + deferred decode
                # commits) — a dispatched-but-uncommitted token is not
                # out yet.
                "tokens_out": sum(1 for p in prefill_info if p["final"])
                + (
                    spec_info["emitted"]
                    if spec_info is not None
                    else committed_tokens
                ),
                "cache_hit_tokens": step_hit_tokens,
                "preempted": preempted,
                "queue_depth": len(self.scheduler.waiting),
                "duration_s": round(time.perf_counter() - t_step_p, 6),
                "time": t_step,
                "dispatch_time": self._step_dispatch_wall,
                "commits": self._step_commits,
                "host_gap_s": self._step_gap,
                "ready_time": self._step_ready_wall,
                "prefill_s": round(self._step_prefill_s, 6),
                "fabric_wait_s": round(self._step_fabric_wait_s, 6),
                "chained": chained_seqs is not None,
                "inflight_depth": len(self._inflight),
            }
            if spec_info is not None:
                record["speculation"] = spec_info
            if self._fabric is not None:
                record["fabric_restored_blocks"] = step_restored
            self.flight_recorder.record_step(record)
        return {
            "num_prefilled": len(plans),
            "num_decoding": len(decoding),
            "occupancy": occupancy,
            "cache_utilization": self.allocator.utilization(),
            "queue_depth": len(self.scheduler.waiting),
            "preempted": preempted,
            "cache_hit_tokens": step_hit_tokens,
            "evictable_blocks": self.allocator.num_evictable,
            "prefill_backlog_tokens": backlog,
        }

    def _try_chain(self, rec: _InflightStep) -> Optional[List[Sequence]]:
        """Chain the in-flight decode into the next dispatch if — and
        only if — the next decode batch would be EXACTLY the dispatched
        batch (same sequences, same slot order: the chained token input
        is slot-aligned on device) AND every +1-position write can be
        covered without preempting anyone (reserve_decode_lookahead).
        On success the chained program is already dispatched when this
        returns; on any mismatch returns None and the caller flushes."""
        for seq, rid in zip(rec.seqs, rec.rids):
            if (
                not seq.is_running
                or seq.prefilling
                or not self.scheduler.is_active(rid)
            ):
                return None
        current = [s for s in self.scheduler.running if not s.prefilling]
        if len(current) != len(rec.seqs) or any(
            a is not b for a, b in zip(current, rec.seqs)
        ):
            return None
        if not self.scheduler.reserve_decode_lookahead(rec.seqs):
            return None
        self._dispatch_chained(rec)
        return rec.seqs

    def _dispatch_chained(self, rec: _InflightStep) -> None:
        """Dispatch the next decode with the in-flight step's on-device
        tokens as input — no host sync anywhere on this path. The
        in-flight token for slot i has not committed yet, so its write
        position is num_cached + 1 and its context covers num_cached + 1
        tokens; both advance deterministically without knowing the
        token's value. Unused slots carry whatever the previous program
        sampled — they scatter into the null block exactly like the sync
        path's zero padding."""
        self._note_dispatch(pipelined=True)
        positions = self._dec_positions
        block_tables = self._dec_block_tables
        context_lens = self._dec_context_lens
        positions.fill(0)
        block_tables.fill(0)
        context_lens.fill(0)
        for i, seq in enumerate(rec.seqs):
            positions[i] = seq.num_cached + 1
            block_tables[i, : len(seq.block_table)] = seq.block_table
            context_lens[i] = seq.num_cached + 1
        tokens_dev = self.runner.decode_async(
            rec.tokens_dev, positions, block_tables, context_lens
        )
        self._inflight.append(
            _InflightStep(rec.seqs, rec.rids, tokens_dev, self._steps)
        )

    def _dispatch_decode_async(self, decoding: List[Sequence]) -> None:
        """Fresh async dispatch from fully committed state (pipeline
        start / after a flush): inputs build exactly like _run_decode,
        but the runner starts an async device->host copy instead of
        blocking — the commit runs one step later (_commit_head)."""
        tokens = self._dec_tokens
        positions = self._dec_positions
        block_tables = self._dec_block_tables
        context_lens = self._dec_context_lens
        tokens.fill(0)
        positions.fill(0)
        block_tables.fill(0)
        context_lens.fill(0)
        for i, seq in enumerate(decoding):
            tokens[i] = seq.last_token
            positions[i] = seq.num_cached
            block_tables[i, : len(seq.block_table)] = seq.block_table
            context_lens[i] = seq.num_cached
        self._note_dispatch(pipelined=False)
        tokens_dev = self.runner.decode_async(
            tokens, positions, block_tables, context_lens
        )
        self._inflight.append(
            _InflightStep(
                list(decoding),
                [s.request.request_id for s in decoding],
                tokens_dev,
                self._steps,
            )
        )

    def _commit_head(self) -> None:
        """Fetch and commit the OLDEST in-flight record — the deferred
        half of a dispatch made one iteration ago. The commit loop is the
        sync path's, one step late: per-sequence poison site, num_cached
        advance, block publication, emission, finish detection.
        Sequences that went inactive since dispatch (finished at the
        previous commit, aborted, preempted on a flush) are skipped —
        their fetched token is the EOS/length overshoot or an orphan, and
        it is dropped before any emission. On a mid-loop exception the
        record stays at the head with commit_idx advanced past the
        already-committed slots, so the server's step retry resumes the
        commit exactly where it stopped; failure_step() attributes the
        exception against this record's DISPATCH index."""
        rec = self._inflight[0]
        ecfg = self.engine_config
        instrument = self._instrument
        t0 = time.perf_counter() if instrument else 0.0
        self._attribution_step = rec.dispatch_step
        if rec.tokens_host is None:
            # Materialize the async copy (usually already done — it has
            # been in flight since dispatch). A failed decode PROGRAM
            # surfaces here, one step after dispatch, attributed above.
            rec.tokens_host = np.asarray(rec.tokens_dev)
            self._last_ready_t = time.perf_counter()
            if instrument:
                self._step_ready_wall = time.time()
        next_tokens = rec.tokens_host
        committed = 0
        while rec.commit_idx < len(rec.seqs):
            i = rec.commit_idx
            seq = rec.seqs[i]
            if (
                not seq.is_running
                or seq.prefilling
                or not self.scheduler.is_active(rec.rids[i])
            ):
                rec.commit_idx += 1
                continue
            self._current_rid = rec.rids[i]
            maybe_fail("llm.decode.seq", detail=rec.rids[i])
            seq.num_cached += 1
            seq.generated.append(int(next_tokens[i]))
            if seq.num_cached % ecfg.block_size == 0:
                self.scheduler.note_filled_blocks(seq)
            rec.commit_idx += 1
            committed += 1
            self._emit(seq)
            self._maybe_finish(seq)
        self._current_rid = None
        self._attribution_step = None
        self._inflight.popleft()
        self._decode_tokens += committed
        self._decode_slot_steps += ecfg.max_decode_slots
        self._step_commits.append(
            {
                "dispatch_step": rec.dispatch_step,
                "time": time.time(),
                "tokens": committed,
                "commit_s": (
                    round(time.perf_counter() - t0, 6)
                    if instrument
                    else None
                ),
            }
        )
        if instrument:
            # The async decode series measures the commit half (fetch +
            # emission loop) — the dispatch half is what the chain hides.
            self._h_step.observe(
                time.perf_counter() - t0, tags=self._step_tags["decode"]
            )

    def _run_prefill_chunks(
        self,
        plans: List[tuple],
        info_out: Optional[List[dict]] = None,
    ) -> int:
        """Run this step's prefill chunk plan ((sequence, token count)
        pairs from Scheduler.schedule_prefill_chunks); returns the prompt
        tokens served from the prefix cache this step. Each chunk commits
        independently (num_cached advances only after its program
        returns), so a failure mid-plan leaves every sequence — including
        the culprit — consistent: a retry re-plans from committed state,
        a dead-letter releases all of the culprit's blocks via the normal
        abort path. Only the FINAL chunk of a prompt produces a token;
        continuation chunks just stream K/V into the cache. With
        instrumentation, `info_out` collects one record per chunk for the
        flight recorder."""
        instrument = self._instrument
        hit_tokens = 0
        t_plan = time.perf_counter() if (instrument and plans) else 0.0
        for seq, take in plans:
            # Per-sequence section: an exception below is attributable to
            # this request (LLMServer._loop fails only it and keeps going).
            rid = seq.request.request_id
            self._current_rid = rid
            first_chunk = seq.num_chunks == 0
            final = take >= seq.prefill_len - seq.num_cached
            if first_chunk:
                maybe_fail("llm.prefill", detail=rid)
            maybe_fail("engine.prefill_chunk", detail=rid)
            offset = seq.num_cached  # cache-matched prefix + prior chunks
            rt = queue_wait = None
            if instrument:
                t0 = time.time()
                rt = self._req_traces.get(rid)
                if rt is not None and rt.queue_start is not None:
                    # The queue ends when the request's FIRST chunk starts
                    # computing (one wait per admission; a preempt-resume
                    # reopens the clock and its first resumed chunk closes
                    # it again).
                    queue_wait = rt.on_admitted(t0)
            was_cow = seq.pending_copy is not None
            if was_cow:
                # Copy-on-write: the last matched block is shared and this
                # prefill writes its final token's K/V into it. pending_copy
                # is cleared only AFTER the device copy lands and the
                # copy-source ref is dropped: if copy_block raises (poison
                # request, injected fault), _release must still see the
                # marker and free src — clearing first leaked the ref and
                # permanently shrank the block pool (found by lint RTL403).
                src, dst = seq.pending_copy
                self.runner.copy_block(src, dst)
                self.allocator.free([src])  # drop admission's copy-source ref
                seq.pending_copy = None
            chunk_ids = seq.prefill_ids[offset : offset + take]
            if offset > 0:
                tok = self.runner.prefill_suffix(
                    chunk_ids, seq.block_table, offset
                )
                if first_chunk:
                    hit_tokens += offset
            else:
                # First chunk from a cold cache: the full-prefill program
                # for this chunk's bucket. Slice the table — the sequence
                # owns blocks for its WHOLE prompt, but this program's
                # block vector is sized for the chunk's bucket.
                tok = self.runner.prefill(
                    chunk_ids,
                    seq.block_table[
                        : blocks_for_tokens(
                            take, self.engine_config.block_size
                        )
                    ],
                )
            self._prefill_tokens += take
            self._prefill_chunk_dispatches += 1
            seq.num_cached = offset + take
            seq.num_chunks += 1
            if final and seq.num_chunks > 1:
                self._chunked_prefill_requests += 1
            # Publish every block this chunk filled: a concurrent request
            # with the same prompt can share the prefix before the whole
            # prompt even finishes prefilling.
            pre_hashes = len(seq.block_hashes)
            self.scheduler.note_filled_blocks(seq)
            if self._publish_on_fill and len(seq.block_hashes) > pre_hashes:
                # Prefill-role handoff: push this chunk's just-filled
                # blocks to the fabric NOW, so they are resident before
                # the request's reply (the barrier the decode-role
                # engine's admission relies on) can possibly seal.
                pushed = self._fabric.put_many(
                    [
                        (
                            seq.block_hashes[j],
                            self.runner.extract_block(seq.block_table[j]),
                        )
                        for j in range(pre_hashes, len(seq.block_hashes))
                    ]
                )
                if pushed:
                    self._fabric_spilled_total += pushed
                    self._fabric_spills.inc(
                        pushed, tags=self._metric_tags
                    )
            if final:
                seq.generated.append(tok)
            if instrument:
                t1 = time.time()
                kind = "cow" if was_cow else ("partial" if offset else "full")
                phase = "partial_prefill" if offset else "prefill"
                bucket = self.engine_config.bucket_for(max(take, 1))
                # ray-tpu: lint-ignore[RTL302] t0/t1 double as span
                # timestamps (wall-clock identity across actors); the
                # histogram delta rides on the same pair
                self._h_step.observe(
                    t1 - t0,
                    tags=(
                        self._step_tags[phase]
                        if final
                        else self._chunk_step_tags[phase]
                    ),
                )
                if queue_wait is not None:
                    self._h_queue.observe(
                        queue_wait, tags=self._metric_tags
                    )
                if rt is not None:
                    first_admission = rt.first_token_s is None
                    rt.on_prefilled(
                        t0, t1, kind, bucket, take, offset,
                        len(seq.generated),
                        chunk=seq.num_chunks - 1, final=final,
                    )
                    if final and first_admission:
                        # TTFT observes exactly once per request: at the
                        # final chunk of its FIRST admission (chunked or
                        # not), when the first token actually exists.
                        self._h_ttft.observe(
                            t1 - rt.submit_s, tags=self._metric_tags
                        )
                if info_out is not None:
                    info_out.append(
                        {
                            "request_id": rid,
                            "kind": kind,
                            "bucket": bucket,
                            "tokens": take,
                            "cached_tokens": offset,
                            "chunk": seq.num_chunks - 1,
                            "final": final,
                        }
                    )
            if final:
                self._emit(seq)
                self._maybe_finish(seq)
        self._current_rid = None
        if instrument and plans:
            # Whole-plan prefill seconds (programs + publication +
            # emission): the ledger's prefill column for this step.
            self._step_prefill_s = time.perf_counter() - t_plan
        return hit_tokens

    def _emit(self, seq: Sequence) -> None:
        cb = self._on_token.get(seq.request.request_id)
        while seq.emitted < len(seq.generated):
            token = seq.generated[seq.emitted]
            seq.emitted += 1
            self._tokens_generated.inc(tags=self._metric_tags)
            if cb is not None:
                cb(token)

    def _maybe_finish(self, seq: Sequence) -> None:
        req = seq.request
        reason = None
        if req.eos_id is not None and seq.generated[-1] == req.eos_id:
            reason = FINISH_EOS
        elif len(seq.generated) >= req.max_new_tokens:
            reason = FINISH_LENGTH
        if reason is not None:
            self.scheduler.finish(seq, reason)
            self._finished(seq)

    def _note_preempt(self, seq: Sequence) -> None:
        """Scheduler preemption hook: drop the proposer's per-request
        state (a stateful proposer's draft blocks must not outlive the
        victim's own KV blocks — the resume re-prefills both caches),
        then close the victim's decode-stretch span, mark the preemption,
        and restart its queue-wait clock."""
        if self._spec is not None:
            self._spec.release(seq.request.request_id)
        rt = self._req_traces.get(seq.request.request_id)
        if rt is not None:
            rt.on_preempt(time.time(), len(seq.generated))

    def _finished(self, seq: Sequence) -> None:
        req_id = seq.request.request_id
        if seq.request.deadline_s is not None:
            # Terminal for any reason: this deadline no longer needs the
            # per-step sweep. Clamped so a double-finish can never drive
            # the gate negative and disable expiry for live requests.
            self._deadline_count = max(0, self._deadline_count - 1)
        if self._spec is not None:
            # Terminal for any reason (finish, abort, dead-letter): the
            # proposer's per-request resources (draft KV blocks) go with
            # the request's own KV blocks.
            self._spec.release(req_id)
        self._on_token.pop(req_id, None)
        rt = self._req_traces.pop(req_id, None)
        if rt is not None:
            now = time.time()
            rt.on_finish(now, seq)
            self._h_e2e.observe(now - rt.submit_s, tags=self._metric_tags)
            n = len(seq.generated)
            if rt.first_token_s is not None and n >= 2:
                # Mean inter-token latency after the first token (TPOT);
                # single-token requests have no decode interval to report.
                self._h_tpot.observe(
                    (now - rt.first_token_s) / (n - 1), tags=self._metric_tags
                )
        cb = self._on_finish.pop(req_id, None)
        if cb is not None:
            cb(seq)

    # ---------------- convenience ----------------

    def generate(
        self,
        prompts: List[List[int]],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
    ) -> List[List[int]]:
        """Run a batch of prompts to completion with continuous batching and
        return their generated token ids, in request order."""
        outputs: List[List[int]] = []
        for prompt in prompts:
            tokens: List[int] = []
            self.add_request(
                prompt,
                max_new_tokens=max_new_tokens,
                eos_id=eos_id,
                on_token=tokens.append,
            )
            outputs.append(tokens)
        while self.has_work():
            self.step()
        return outputs

    def stats(self) -> dict:
        elapsed = max(time.monotonic() - self._start, 1e-9)
        # Per-chip vs aggregate cache bytes: the pools shard on the head
        # axis, so each chip holds aggregate / tensor_parallel_size — the
        # number that decides whether a model's cache fits per-chip HBM.
        pool_bytes = self.runner.kv_pool_bytes()
        fabric_store = None
        if self._fabric is not None:
            # One store RPC per stats scrape (never per step): the store
            # is shared, so occupancy only has one true source.
            fabric_store = self._fabric.stats()
            if fabric_store:
                self._fabric_bytes_used.set(
                    float(fabric_store.get("bytes_used", 0)),
                    tags=self._metric_tags,
                )
        return {
            "engine_id": self._metric_tags["engine"],
            "attn_impl": self._attn_impl,
            "kv_cache_dtype": self.runner.kv_cache_dtype_str,
            "tensor_parallel_size": self._tp,
            "kv_pool_bytes": pool_bytes["aggregate"],
            "kv_pool_bytes_per_shard": pool_bytes["per_shard"],
            # PartitionSpec of the live pools (None at tp=1): proof the
            # cache is still head-sharded after whatever traffic ran.
            "kv_pool_sharding": self.runner.pool_sharding_spec(),
            # Weight count for the fleet ledger's MFU estimate (decode
            # FLOPs ~= 2 * model_params per generated token). Counted
            # once at runner init, not per scrape.
            "model_params": getattr(self.runner, "num_params", None),
            "host_transfer_bytes": self._host_transfer_bytes(),
            "steps": self._steps,
            "decode_tokens": self._decode_tokens,
            # Async step loop (EngineConfig.async_scheduling) + the
            # host-gap apparatus it is measured by: mean/last host time
            # between consecutive device dispatches (0 for a chained
            # async dispatch — it beat the previous step's fetch), and
            # how many records are dispatched-but-uncommitted right now.
            "async_scheduling": self._async,
            "inflight_steps": len(self._inflight),
            "host_gap_samples": self._host_gap_count,
            "host_gap_total_s": self._host_gap_total,
            "host_gap_mean_s": (
                self._host_gap_total / self._host_gap_count
                if self._host_gap_count
                else None
            ),
            "host_gap_last_s": self._host_gap_last,
            "mean_occupancy": (
                self._decode_tokens / self._decode_slot_steps
                if self._decode_slot_steps
                else 0.0
            ),
            "preemptions": self.scheduler.num_preemptions,
            "num_preemptions": self.scheduler.num_preemptions,
            "cache_utilization": self.allocator.utilization(),
            "queue_depth": len(self.scheduler.waiting),
            "num_running": len(self.scheduler.running),
            "prefill_tokens": self._prefill_tokens,
            "prefill_token_budget": self._prefill_budget,
            "prefill_backlog_tokens": (
                self.scheduler.prefill_backlog_tokens()
            ),
            "prefill_chunk_dispatches": self._prefill_chunk_dispatches,
            "chunked_prefill_requests": self._chunked_prefill_requests,
            "prefix_cache_hit_tokens": self._cache_hit_tokens,
            "prefix_cache_hit_rate": (
                self._cache_hit_tokens / max(self._prefill_tokens, 1)
            ),
            "evictable_blocks": self.allocator.num_evictable,
            "prefix_cache_evictions": self.allocator.num_evictions,
            "cow_blocks": self.scheduler.num_cow_blocks,
            "engine_role": self.engine_config.engine_role,
            "kv_fabric": (
                self.engine_config.kv_fabric.name
                if self.engine_config.kv_fabric is not None
                else "off"
            ),
            "fabric_spill_blocks": self._fabric_spilled_total,
            "fabric_restore_blocks": self._fabric_restored_total,
            "fabric_hit_blocks": self._fabric_hit_total,
            "fabric_restored_tokens": self._fabric_restored_tokens,
            "fabric_hit_rate": (
                self._fabric_restored_tokens / max(self._prefill_tokens, 1)
            ),
            "fabric_store": fabric_store,
            "fabric_timeouts": self._fabric_timeout_total,
            "num_dead_letters": len(self._dead_letters),
            # Overload control plane: bounded-admission rejections and
            # deadline expiries (llm_engine_shed_requests /
            # llm_engine_expired_requests counters carry the same totals).
            "shed_requests": self._shed_total,
            "expired_requests": self._expired_total,
            "max_queue_len": self.engine_config.max_queue_len,
            "max_queue_tokens": self.engine_config.max_queue_tokens,
            "speculation": (
                self._spec.name if self._spec is not None else "off"
            ),
            "spec_proposed_tokens": self._spec_proposed_total,
            "spec_accepted_tokens": self._spec_accepted_total,
            "spec_acceptance_rate": (
                self._spec_accepted_total
                / max(self._spec_proposed_total, 1)
            ),
            "spec_verify_steps": self._verify_steps,
            # Draft-mirror pool occupancy (0 without a stateful proposer):
            # must return to 0 when no requests are in flight — leaked
            # mirror blocks after aborts/disconnects show up here.
            "spec_draft_pool_allocated": (
                self._spec.allocator.num_allocated
                if self._spec is not None
                and getattr(self._spec, "allocator", None) is not None
                else 0
            ),
            "kv_pool_allocated": self.allocator.num_allocated,
            # > 1.0 means verification is amortizing decode steps: tokens
            # emitted per verify-program dispatch, correction included.
            "spec_tokens_per_verify_step": (
                self._spec_emitted_total / max(self._verify_steps, 1)
            ),
            "uptime_s": elapsed,
        }


class _RequestState:
    __slots__ = ("tokens", "done", "seq", "error")

    def __init__(self):
        self.tokens: "queue.Queue" = queue.Queue()
        self.done = threading.Event()
        self.seq: Optional[Sequence] = None
        self.error: Optional[BaseException] = None


_STREAM_END = object()


class LLMServer:
    """Engine actor: background step loop + blocking / streaming generate.

    Deploy with `ray_tpu.remote(LLMServer).options(max_concurrency=N)` so
    concurrent generate calls overlap; they are continuous-batched inside
    the one engine. `generate_stream` is a generator method — call it with
    `.options(num_returns="streaming")` on the actor handle.
    """

    def __init__(
        self,
        model_config: Optional[GPTConfig] = None,
        engine_config: Optional[EngineConfig] = None,
        params=None,
        seed: int = 0,
        warmup: bool = True,
        draft_params=None,
    ):
        self._engine = LLMEngine(
            model_config, engine_config, params=params, seed=seed,
            draft_params=draft_params,
        )
        if warmup:
            # Compile every prefill bucket and the decode program now, while
            # the actor is still initializing — a Serve deployment only
            # reports healthy afterwards, so cold-start compile never runs
            # under live traffic (nor under the controller's health probes).
            # Warmup generations are NOT real requests: suppress per-request
            # instrumentation so multi-second XLA compiles don't land in the
            # TTFT/e2e SLO histograms or the trace buffer (the flight
            # recorder's compile events capture warmup cost instead).
            # Speculation is suppressed too: the generate-based warmup
            # rounds must deterministically exercise every prefill/decode
            # bucket (an all-zeros prompt is maximally repetitive, so the
            # n-gram proposer would reroute them through verify); the
            # verify buckets get their own dedicated compile pass below.
            # The KV fabric is suppressed during warmup too, hooks and
            # all: warmup's zero-prompt rounds must exercise the FULL
            # prefill program per bucket, but a fabric warmed by an
            # earlier replica's warmup would satisfy them as restores
            # (partial prefill), silently skipping the compile — and the
            # publish/spill side would flood the shared store with
            # zero-block entries every replica start.
            # Async stepping is suppressed during warmup as well: the
            # generate-based rounds must compile each bucket program in
            # a deterministic order with deterministic step counts, and
            # the async loop's chained decode dispatches the SAME
            # compiled program anyway (identical avals — a device token
            # array and a host one trace alike), so async mode needs no
            # warmup pass of its own.
            instrumented = self._engine._instrument
            spec = self._engine._spec
            publish = self._engine._publish_on_fill
            on_evict = self._engine.allocator.on_evict
            probe = self._engine.scheduler.fabric_probe
            async_loop = self._engine._async
            self._engine._instrument = False
            # ray-tpu: lint-ignore[RTL403] deliberate temporary clear —
            # the finally below restores _spec on every path, so no
            # exception can skip the consumer of the saved value
            self._engine._spec = None
            self._engine._publish_on_fill = False
            self._engine.allocator.on_evict = None
            self._engine.scheduler.fabric_probe = None
            self._engine._async = False
            try:
                self._warmup()
            finally:
                self._engine._instrument = instrumented
                self._engine._spec = spec
                self._engine._publish_on_fill = publish
                self._engine.allocator.on_evict = on_evict
                self._engine.scheduler.fabric_probe = probe
                self._engine._async = async_loop
            if spec is not None:
                self._warmup_verify(spec)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._requests: Dict[str, _RequestState] = {}
        self._shutdown = False
        self._wedged = False
        self._consecutive_step_failures = 0
        self._thread = threading.Thread(
            target=self._loop, name="llm-engine-loop", daemon=True
        )
        self._thread.start()

    def _warmup(self) -> None:
        ecfg = self._engine.engine_config
        buckets = ecfg.buckets()
        # With a chunked-prefill budget, prompts never feed more than
        # bucket_for(budget) tokens per dispatch, so larger bucket
        # programs are UNREACHABLE — warming them would waste init time
        # and charge compile blame for programs live traffic can't run.
        # chunk_widths() is exactly the reachable set (== buckets() when
        # chunking is off).
        widths = ecfg.chunk_widths()
        for bucket in widths:
            # Prompt length landing in this bucket, shaped so the whole
            # request passes admission (lifetime within the largest
            # bucket and max_model_len). 2 tokens when room allows: the
            # second forces a decode step, compiling that program too.
            n = bucket if bucket < buckets[-1] else bucket - 1
            n = min(n, ecfg.max_model_len - 1)
            budget = min(2, ecfg.max_model_len - n)
            if n < 1:
                continue
            # Each round must exercise the FULL prefill program: drop
            # the previous round's cached zero-blocks, or this prompt
            # would hit them and take the partial-prefill path, leaving
            # this bucket's full program uncompiled.
            self._engine.allocator.reset_prefix_cache()
            t0 = time.monotonic()
            try:
                self._engine.generate([[0] * n], max_new_tokens=budget)
            except ValueError:
                # Bucket unwarmable under this config (e.g. the block
                # pool is smaller than the bucket); requests that large
                # are rejected at admission anyway.
                continue
            # Cold-compile blame: almost all of this round is XLA
            # compiling the bucket's full-prefill program (plus, on the
            # first round, the decode program).
            self._engine.flight_recorder.record_compile(
                "prefill", bucket, time.monotonic() - t0
            )
        if ecfg.enable_prefix_caching:
            # Also compile every partial-prefill bucket and the
            # copy-on-write block copy, so cache hits never trigger a
            # cold compile under live traffic. Each round seeds exactly
            # one cached block of zeros, then prefills a zero-prompt
            # whose uncached suffix lands in the target bucket; the
            # duplicate-prompt round at the end exercises the
            # fully-cached path (CoW + smallest suffix bucket).
            alloc = self._engine.allocator
            bs = ecfg.block_size
            for bucket in widths + (0,):
                alloc.reset_prefix_cache()
                n = min(bs + bucket, ecfg.max_model_len - 1, buckets[-1])
                t0 = time.monotonic()
                try:
                    self._engine.generate([[0] * bs], max_new_tokens=1)
                    if n > bs:
                        self._engine.generate([[0] * n], max_new_tokens=1)
                    else:  # CoW round: repeat the fully-cached prompt
                        self._engine.generate([[0] * bs], max_new_tokens=1)
                except ValueError:
                    continue
                self._engine.flight_recorder.record_compile(
                    "cow" if n <= bs else "partial_prefill",
                    0 if n <= bs else bucket,
                    time.monotonic() - t0,
                )
            alloc.reset_prefix_cache()
        if ecfg.prefill_token_budget is not None:
            # Chunked prefill dispatches BOTH prefill program families at
            # every reachable width: the full program for a cold first
            # chunk, the partial program for every continuation chunk
            # (and, with prefix caching off, the generate rounds above
            # never compiled the partial family at all). Compile each
            # (width × program) pair directly against the null block —
            # writes land in block 0, no allocator state is touched, and
            # already-compiled pairs are cache hits — so no chunk can
            # cold-compile under live traffic.
            runner = self._engine.runner
            null_table = [0] * ecfg.max_blocks_per_seq
            for w in widths:
                t0 = time.monotonic()
                runner.prefill([0] * w, [0])
                runner.prefill_suffix([0] * w, null_table, 0)
                self._engine.flight_recorder.record_compile(
                    "chunk_prefill", w, time.monotonic() - t0
                )

    def _warmup_verify(self, spec) -> None:
        """Compile every k-token verify bucket program plus whatever the
        proposer owns (the draft model's prefill/decode programs), so the
        first speculative step under live traffic never cold-compiles.
        The synthetic verify calls run against all-null block tables:
        writes land in the null block (the masked-lane convention) and
        touch no allocator state."""
        ecfg = self._engine.engine_config
        runner = self._engine.runner
        slots = ecfg.max_decode_slots
        nb = ecfg.max_blocks_per_seq
        for s_bucket in ecfg.verify_buckets():
            t0 = time.monotonic()
            runner.verify(
                np.zeros((slots, s_bucket), np.int32),
                np.zeros((slots, nb), np.int32),
                np.zeros((slots,), np.int32),
                np.full((slots,), s_bucket, np.int32),
            )
            self._engine.flight_recorder.record_compile(
                "verify", s_bucket, time.monotonic() - t0
            )
        t0 = time.monotonic()
        spec.warmup()
        self._engine.flight_recorder.record_compile(
            f"proposer:{spec.name}", 0, time.monotonic() - t0
        )

    # ---------------- engine loop ----------------

    def _loop(self) -> None:
        max_failures = self._engine.engine_config.max_consecutive_step_failures
        while True:
            with self._work:
                while not self._shutdown and not self._engine.has_work():
                    self._work.wait()
                if self._shutdown:
                    return
            # Step outside the condition wait but under the lock: the engine
            # is single-threaded and submissions mutate scheduler state.
            with self._lock:
                try:
                    self._engine.step()
                    self._consecutive_step_failures = 0
                    continue
                except BaseException as exc:
                    self._consecutive_step_failures += 1
                    # Attribution comes FIRST: an isolatable poison request
                    # must be dead-lettered even when the consecutive-
                    # failure counter is at the threshold (otherwise
                    # max_consecutive_step_failures=1 would disable
                    # isolation entirely).
                    culprit = self._engine.culprit_for(exc)
                    recorder = self._engine.flight_recorder
                    # Under async_scheduling a commit-time failure is
                    # attributed one step late: failure_step() resolves
                    # to the in-flight record's DISPATCH index (sync
                    # mode: the current step, as before).
                    step_idx = self._engine.failure_step()
                    if culprit is not None:
                        # Poison-request isolation: fail only the culpable
                        # request (dead-letter + KV release) and keep
                        # stepping for everyone else. The waiter's error is
                        # set BEFORE fail_request fires its finish callback
                        # so the caller never sees a clean finish.
                        state = self._requests.get(culprit)
                        if state is not None and not state.done.is_set():
                            state.error = PoisonRequestError(
                                request_id=culprit, cause=exc
                            )
                        if self._engine.fail_request(culprit, exc):
                            # Contained: the culprit is out of the batch, so
                            # the engine is making progress — only steps
                            # that fail WITHOUT an isolatable culprit count
                            # toward the wedge threshold (a stream of poison
                            # requests must not take down the replica).
                            self._consecutive_step_failures = 0
                        recorder.record_failure(
                            step_idx, repr(exc), request_id=culprit,
                            action="dead_letter",
                        )
                        continue
                    if self._consecutive_step_failures < max_failures:
                        recorder.record_failure(
                            step_idx, repr(exc), action="retry"
                        )
                        # Unattributable failure (e.g. the batched decode
                        # program itself): per-sequence state only mutates
                        # after the risky calls return, so retrying the
                        # step is safe. A deterministic failure trips the
                        # consecutive-failures threshold and wedges below.
                        continue
                    # Wedged: broadcast to every waiter while still holding
                    # the lock so no submission can slip in between the
                    # error broadcast and the thread actually dying; the
                    # Serve controller's next health probe then replaces
                    # the replica.
                    recorder.record_failure(
                        step_idx, repr(exc), action="wedged"
                    )
                    self._wedged = True
                    self._shutdown = True
                    self._engine.close_traces(exc)
                    for state in self._requests.values():
                        if not state.done.is_set():
                            state.error = exc
                            state.tokens.put(_STREAM_END)
                            state.done.set()
                    import traceback

                    traceback.print_exc()
                    return

    def _submit(
        self,
        prompt_ids: List[int],
        max_new_tokens: Optional[int],
        eos_id: Optional[int],
        request_id: Optional[str],
        deadline_s: Optional[float] = None,
    ) -> tuple[str, _RequestState]:
        state = _RequestState()

        def on_finish(seq: Sequence) -> None:
            state.seq = seq
            state.tokens.put(_STREAM_END)
            state.done.set()

        with self._work:
            if self._shutdown or not self._thread.is_alive():
                raise RuntimeError(
                    "LLM engine loop is not running (shut down or crashed); "
                    "restart the engine actor"
                )
            if request_id is not None and request_id in self._requests:
                raise ValueError(
                    f"request_id {request_id!r} already has an in-flight "
                    "generation on this server"
                )
            # Bounded admission fails fast HERE: add_request raises a
            # typed, retryable EngineOverloadedError before any state
            # lands in _requests — the caller (and through it the Serve
            # router) sees the shed in one lock acquisition, never after
            # queueing.
            rid = self._engine.add_request(
                prompt_ids,
                max_new_tokens=max_new_tokens,
                eos_id=eos_id,
                request_id=request_id,
                on_token=state.tokens.put,
                on_finish=on_finish,
                deadline_s=deadline_s,
            )
            self._requests[rid] = state
            self._work.notify_all()
        return rid, state

    # ---------------- public API ----------------

    def generate(
        self,
        prompt_ids: List[int],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
        request_id: Optional[str] = None,
        timeout_s: float = 120.0,
    ) -> dict:
        """Blocking generation. `timeout_s` is the request's END-TO-END
        deadline: it bounds this call's wait AND rides into the engine as
        an absolute monotonic deadline, so a request that cannot finish in
        time is dropped from the queue before its prefill ever runs (or
        aborted mid-decode with its blocks reclaimed) instead of decoding
        for a caller that already gave up. Either side tripping first
        raises TimeoutError."""
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        rid, state = self._submit(
            prompt_ids, max_new_tokens, eos_id, request_id, deadline
        )
        try:
            if not state.done.wait(timeout=timeout_s):
                # The request may have finished in the instant between the
                # wait expiring and the abort landing; only a successful
                # abort (it was still queued/running) is a real timeout —
                # otherwise fall through and deliver the completed result.
                if self.abort(rid) or not state.done.is_set():
                    raise TimeoutError(
                        f"generation {rid} timed out after {timeout_s}s"
                    )
            if state.error is not None:
                raise state.error
            if (
                state.seq is not None
                and state.seq.finish_reason == FINISH_EXPIRED
            ):
                # The ENGINE enforced the deadline (queued expiry or
                # mid-decode abort) before this thread's own wait tripped:
                # same contract, same error.
                raise TimeoutError(
                    f"generation {rid} exceeded its {timeout_s}s deadline"
                )
            token_ids = []
            while True:
                item = state.tokens.get_nowait()
                if item is _STREAM_END:
                    break
                token_ids.append(item)
            return {
                "request_id": rid,
                "token_ids": token_ids,
                "finish_reason": state.seq.finish_reason if state.seq else None,
                "num_preemptions": state.seq.num_preemptions if state.seq else 0,
            }
        finally:
            with self._lock:
                self._requests.pop(rid, None)

    def generate_stream(
        self,
        prompt_ids: List[int],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
        request_id: Optional[str] = None,
        timeout_s: float = 120.0,
        stream_idle_timeout_s: Optional[float] = None,
    ):
        """Yields token ids as the engine produces them.

        `timeout_s` is the END-TO-END deadline — the same meaning as the
        blocking path (it previously meant the per-token gap here; that
        drift is exactly what `stream_idle_timeout_s` now carries). The
        deadline rides into the engine, so an expiring stream is aborted
        with its blocks reclaimed and this generator raises TimeoutError
        after yielding whatever was already emitted.
        `stream_idle_timeout_s` (optional) additionally bounds the gap
        between consecutive tokens — the old `timeout_s` semantics for
        callers that want a liveness check tighter than the deadline."""
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        rid, state = self._submit(
            prompt_ids, max_new_tokens, eos_id, request_id, deadline
        )
        try:
            while True:
                wait = stream_idle_timeout_s
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    wait = (
                        remaining
                        if wait is None
                        else min(wait, remaining)
                    )
                if wait is not None and wait < 0.0:
                    wait = 0.0  # Queue.get rejects negative timeouts
                try:
                    item = state.tokens.get(timeout=wait)
                except queue.Empty:
                    self.abort(rid)
                    if (
                        deadline is not None
                        and time.monotonic() >= deadline
                    ):
                        raise TimeoutError(
                            f"generation {rid} exceeded its {timeout_s}s "
                            "deadline"
                        ) from None
                    raise TimeoutError(
                        f"generation {rid} produced no token for "
                        f"{stream_idle_timeout_s}s"
                    ) from None
                if item is _STREAM_END:
                    break
                yield item
            if state.error is not None:
                raise state.error
            if (
                state.seq is not None
                and state.seq.finish_reason == FINISH_EXPIRED
            ):
                raise TimeoutError(
                    f"generation {rid} exceeded its {timeout_s}s deadline"
                )
        finally:
            # Closed before exhaustion (consumer disconnected / stream task
            # cancelled → GeneratorExit at the yield): the request is still
            # occupying KV blocks (and, with speculation=draft, mirror
            # blocks) to generate tokens nobody will read — abort it so the
            # pool returns to steady state now. A finished request is no
            # longer active, so the abort is a no-op on the normal path.
            with self._lock:
                self._requests.pop(rid, None)
                self._engine.abort(rid)

    def abort(self, request_id: str) -> bool:
        with self._lock:
            return self._engine.abort(request_id)

    def metrics(self) -> dict:
        with self._lock:
            stats = self._engine.stats()
            stats["wedged"] = self._wedged
            stats["consecutive_step_failures"] = self._consecutive_step_failures
            return stats

    def autoscaling_snapshot(self) -> dict:
        """Compact SLO signal bundle for the serve controller's
        LLMAutoscalingPolicy: the engine's queue-time and TTFT histogram
        series (snapshotted engine-side so the numbers are correct even
        when the engine actor runs out-of-process from the controller)
        plus the prefill backlog and load counts. The controller diffs
        two snapshots to get a look-back window — scale-up triggers on
        RECENT p99, not the engine's lifetime percentile."""
        with self._lock:
            e = self._engine
            return {
                "engine_id": e._metric_tags["engine"],
                "queue_depth": len(e.scheduler.waiting),
                "num_running": len(e.scheduler.running),
                # Decode occupancy bound: num_running at max_decode_slots
                # means the engine is decode-SATURATED even when the
                # admission-time histograms are silent (long generations,
                # no new arrivals) — the policy must not read that
                # silence as idleness and scale the fleet down.
                "max_decode_slots": e.engine_config.max_decode_slots,
                "prefill_backlog_tokens": int(
                    e.scheduler.prefill_backlog_tokens()
                ),
                "queue_time": e._h_queue.snapshot(e._metric_tags),
                "ttft": e._h_ttft.snapshot(e._metric_tags),
            }

    def dead_letters(self) -> List[dict]:
        """Records of requests failed in isolation after poisoning an
        engine step (id, prompt hash, error, step), oldest first."""
        with self._lock:
            return self._engine.dead_letters()

    def shed_requests(self) -> List[dict]:
        """Records of submissions rejected by bounded admission or dead
        on arrival (id, reason, queue depth, retry-after hint), oldest
        first — the overload plane's dead_letters()."""
        with self._lock:
            return self._engine.shed_requests()

    def flight_record(self, steps_limit: Optional[int] = None) -> dict:
        """The engine flight recorder: bounded rings of per-step records
        (phase, batch size, tokens, buckets, cache hits, preemptions,
        duration), warmup compile events (cold-compile blame), and step
        failures with the action taken (dead_letter / retry / wedged)."""
        with self._lock:
            return self._engine.flight_recorder.snapshot(steps_limit)

    def observability_snapshot(
        self, steps_limit: Optional[int] = None
    ) -> dict:
        """metrics + dead letters + flight recorder in ONE actor round trip
        (the dashboard /api/llm panel polls this; three separate RPCs per
        engine per refresh would triple the scrape's exposure to a busy
        engine's lock)."""
        with self._lock:
            e = self._engine
            stats = e.stats()
            stats["wedged"] = self._wedged
            stats["consecutive_step_failures"] = self._consecutive_step_failures
            return {
                "metrics": stats,
                "dead_letters": e.dead_letters(),
                "shed_requests": e.shed_requests(),
                "flight_record": e.flight_recorder.snapshot(steps_limit),
                # Engine-side histogram snapshots for cross-replica
                # aggregation (util.metrics.merge_snapshots): snapshotted
                # here so the numbers are correct even when the engine
                # actor runs out-of-process from the collector.
                "histograms": {
                    "llm_request_ttft_seconds": e._h_ttft.snapshot(
                        e._metric_tags
                    ),
                    "llm_request_time_per_output_token_seconds": (
                        e._h_tpot.snapshot(e._metric_tags)
                    ),
                    "llm_request_queue_time_seconds": e._h_queue.snapshot(
                        e._metric_tags
                    ),
                    "llm_request_e2e_seconds": e._h_e2e.snapshot(
                        e._metric_tags
                    ),
                    "llm_engine_step_host_gap_seconds": (
                        e._h_host_gap.snapshot(e._metric_tags)
                    ),
                },
            }

    def reset_prefix_cache(self) -> None:
        """Drop all cached-but-unreferenced KV blocks (e.g. after swapping
        the served params, whose cached activations would be stale)."""
        with self._lock:
            self._engine.allocator.reset_prefix_cache()

    def flush_kv_fabric(self) -> int:
        """Demote the engine's cached-but-unreferenced KV blocks into the
        fabric (the drain path's cache preservation — called by the
        ingress replica's shutdown before the engine actor dies); returns
        blocks resident in the fabric afterwards, 0 without a fabric."""
        with self._lock:
            return self._engine.flush_kv_fabric()

    def num_pending(self) -> int:
        with self._lock:
            return len(self._engine.scheduler.waiting) + len(
                self._engine.scheduler.running
            )

    def check_health(self) -> bool:
        # ray-tpu: lint-ignore[RTL201] atomic bool read; taking the engine
        # lock here would park the health probe behind a full step (or a
        # bucket compile) and make the controller churn healthy replicas
        return self._thread.is_alive() and not self._wedged

    def shutdown(self) -> None:
        # Preserve the prefix cache across the actor's death: flush the
        # evictable keyed blocks into the fabric (no-op without one)
        # before the step loop stops. Best effort — shutdown proceeds
        # regardless.
        try:
            self.flush_kv_fabric()
        except Exception:
            pass
        with self._work:
            self._shutdown = True
            # Fail in-flight requests promptly instead of leaving their
            # callers to run out their full wait timeout.
            exc = RuntimeError("LLM engine shut down with requests in flight")
            if self._requests:
                self._engine.close_traces(exc)
            for state in self._requests.values():
                if not state.done.is_set():
                    state.error = exc
                    state.tokens.put(_STREAM_END)
                    state.done.set()
            self._work.notify_all()
        self._thread.join(timeout=10.0)
