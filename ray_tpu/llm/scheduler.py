"""Iteration-level (continuous batching) scheduler.

Every engine step: admit queued prompts into free decode slots while the
cache has room, continue every running sequence by one token, and preempt
under cache pressure. Preemption is recompute-style: the victim's blocks
are freed and it re-enters the front of the waiting queue with its
already-generated tokens folded into the prompt, so a later prefill
restores its state exactly (tokens already streamed out are not re-emitted
— `emitted` survives preemption).

With automatic prefix caching (BlockAllocator docstring) admission is
prefix-aware: the longest chain of cached full blocks matching the head of
`prefill_ids` is shared via refcount bumps, and only the uncached tail is
allocated and recomputed (the engine's partial-prefill program). Because a
preempted victim's full blocks stay cached-but-evictable, recompute
preemption becomes nearly free — the resume prefill is mostly cache hits
unless the pool was under enough pressure to really evict them.

Chunked prefill (EngineConfig.max_prefill_tokens_per_step) splits an
admitted prompt's uncached tail into block-aligned chunks fed over several
engine steps: a sequence is admitted with its whole block table, stays
`prefilling` while num_cached < prefill_len, and only joins the decode
batch once the last chunk commits — so one long prompt never monopolizes
an engine step, and decode latency for every in-flight request stays flat
while the prompt streams in.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ray_tpu.llm.cache import (
    BlockAllocator,
    CacheOutOfBlocks,
    blocks_for_tokens,
    hash_block_tokens,
    prefix_block_hashes,
)


FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_ABORTED = "aborted"
FINISH_ERROR = "error"  # dead-lettered after poisoning an engine step
FINISH_EXPIRED = "expired"  # end-to-end deadline passed before completion

_arrival = itertools.count()


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_ids: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    # Absolute MONOTONIC deadline (time.monotonic() seconds) after which
    # the request must stop consuming engine resources: still queued →
    # dropped before its prefill ever runs; decoding → aborted with its
    # blocks reclaimed. None (the default) = no deadline, the pre-deadline
    # behavior bit-for-bit. Derived from the client timeout at submission.
    deadline_s: Optional[float] = None


class Sequence:
    """One request's in-flight state."""

    def __init__(self, request: Request):
        self.request = request
        self.generated: List[int] = []
        self.block_table: List[int] = []
        self.num_cached = 0  # tokens whose K/V sit in the paged cache
        self.emitted = 0  # generated tokens already streamed to the caller
        self.arrival = next(_arrival)
        self.finish_reason: Optional[str] = None
        self.num_preemptions = 0
        # Membership flag so a full-slot engine step stays linear (no
        # `seq in running` list scans).
        self.is_running = False
        # Chain keys of this sequence's full, cached blocks, in order.
        self.block_hashes: List[int] = []
        # Copy-on-write owed by the engine before this sequence's prefill:
        # (src, dst) device block copy. Admission holds an extra ref on src
        # until the copy lands.
        self.pending_copy: Optional[Tuple[int, int]] = None
        # Chunked-prefill state machine: admitted → prefilling(offset =
        # num_cached) → decoding. Set at admission to len(prefill_ids) at
        # that moment; the sequence is mid-prefill while num_cached is
        # below it (prefill_ids itself grows as tokens are generated, so
        # the target must be pinned). A preempt-resume re-admission
        # re-pins it, so resumes re-chunk.
        self.prefill_len = 0
        # Chunk dispatches since the current admission (0 = none yet); the
        # engine uses it for first-chunk bookkeeping and chunk-indexed
        # observability records.
        self.num_chunks = 0
        # KV-fabric restore plan: (block, chain_hash) pairs the engine must
        # copy in from the fabric (allocate happened at admission; the
        # engine copies in, then registers — in that order) before this
        # sequence's first prefill chunk. num_cached does NOT cover these
        # until each restore commits, so a failed restore needs no
        # rollback: the slot simply stays a plain prefill target.
        self.pending_restore: List[Tuple[int, int]] = []

    @property
    def prefill_ids(self) -> List[int]:
        # After a preemption the generated suffix is recomputed as prompt.
        return self.request.prompt_ids + self.generated

    @property
    def prefilling(self) -> bool:
        """True while an admitted sequence still has prompt tokens to feed
        (chunked prefill spreads them over several engine steps). A
        prefilling sequence holds its blocks and a decode slot but never
        enters the decode/verify batch — it would read K/V that was never
        computed."""
        return self.is_running and self.num_cached < self.prefill_len

    @property
    def last_token(self) -> int:
        return self.generated[-1] if self.generated else self.request.prompt_ids[-1]

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None


class Scheduler:
    def __init__(
        self,
        allocator: BlockAllocator,
        max_decode_slots: int,
        max_blocks_per_seq: int,
    ):
        self.allocator = allocator
        self.max_decode_slots = max_decode_slots
        self.max_blocks_per_seq = max_blocks_per_seq
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []  # arrival order
        self._active: Dict[str, Sequence] = {}  # request_id -> waiting|running
        self.num_preemptions = 0
        self.num_cow_blocks = 0
        # Observability hook: called with the victim right after it re-enters
        # the waiting queue (engine closes its decode-stretch span and
        # restarts its queue-wait clock). Fires only on preemption, so the
        # steady-state decode path pays nothing for it.
        self.on_preempt = None
        # KV-fabric probe: called with the chain hashes past the device
        # match, returns per-hash membership in the fabric's host tier
        # (KVFabricClient.contains). None (the default) keeps admission
        # exactly the pre-fabric device-only path.
        self.fabric_probe = None

    # ---------------- queue management ----------------

    def add(self, seq: Sequence) -> None:
        rid = seq.request.request_id
        if rid in self._active:
            raise ValueError(f"request_id {rid!r} is already active")
        self._active[rid] = seq
        self.waiting.append(seq)

    def is_active(self, request_id: str) -> bool:
        return request_id in self._active

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def abort(self, request_id: str) -> Optional[Sequence]:
        seq = self._active.pop(request_id, None)
        if seq is None:
            return None
        if seq.is_running:
            self.running.remove(seq)
            seq.is_running = False
            self._release(seq)
        else:
            self.waiting.remove(seq)
        seq.finish_reason = FINISH_ABORTED
        return seq

    # ---------------- deadline expiry ----------------

    def expire_waiting(self, now: float) -> List[Sequence]:
        """Drop every QUEUED sequence whose deadline has passed — before it
        can cost a prefill program. A waiting sequence owns no blocks (a
        preempt-resume victim released its table when preempted), so expiry
        here is pure bookkeeping: pop from the queue, deactivate, mark
        FINISH_EXPIRED. Returns the expired sequences so the engine can
        notify waiters and write expiry records. `now` is monotonic-clock,
        matching Request.deadline_s."""
        expired = [
            s
            for s in self.waiting
            if s.request.deadline_s is not None
            and now >= s.request.deadline_s
        ]
        for seq in expired:
            self.waiting.remove(seq)
            self._active.pop(seq.request.request_id, None)
            seq.finish_reason = FINISH_EXPIRED
        return expired

    def expired_running(self, now: float) -> List[Sequence]:
        """RUNNING sequences whose deadline has passed. Selection only —
        the engine finishes each through its normal teardown path so KV
        blocks, draft-mirror blocks, and any lookahead reservation are all
        reclaimed (and, under async_scheduling, so the deferred-commit
        loop's inactive-sequence skip drops the in-flight orphan token)."""
        return [
            s
            for s in self.running
            if s.request.deadline_s is not None
            and now >= s.request.deadline_s
        ]

    # ---------------- admission (prefill) ----------------

    def schedule_prefills(self, max_prefills: int) -> List[Sequence]:
        """Admit waiting sequences into free slots, FIFO, while the cache
        can hold their full prompt (plus-generated, after preemption)."""
        admitted: List[Sequence] = []
        while (
            self.waiting
            and len(self.running) < self.max_decode_slots
            and len(admitted) < max_prefills
        ):
            seq = self.waiting[0]
            if not self._admit(seq):
                break  # head-of-line blocking is deliberate: FIFO fairness
            self.waiting.popleft()
            seq.is_running = True
            # Pin the chunking target: prefill_ids grows as the sequence
            # generates, so "fully prefilled" must mean the length at
            # admission, not the live property.
            seq.prefill_len = len(seq.prefill_ids)
            seq.num_chunks = 0
            admitted.append(seq)
            self.running.append(seq)
        return admitted

    def schedule_prefill_chunks(
        self, token_budget: Optional[int]
    ) -> List[Tuple[Sequence, int]]:
        """Plan this step's prefill work: walk the running list in arrival
        order and give each still-prefilling sequence the next chunk of its
        prompt, spending at most `token_budget` tokens across the step
        (None = unlimited: each sequence's whole remainder in one chunk,
        the pre-chunking behavior). Non-final chunks are rounded down to a
        block boundary so every chunk but the last fills whole blocks
        (prefix-cache publication and CoW stay block-aligned). The oldest
        prefilling sequence always gets at least one block when any budget
        remains, so chunked requests make monotonic progress; decode slots
        are untouched — decode-ready sequences batch every step regardless
        of how much prefill is in flight."""
        plans: List[Tuple[Sequence, int]] = []
        remaining = token_budget
        for seq in self.running:
            if not seq.prefilling:
                continue
            left = seq.prefill_len - seq.num_cached
            if remaining is None:
                take = left
            else:
                if remaining <= 0:
                    break
                take = min(left, remaining)
                if take < left:
                    # Keep the chunk block-aligned unless it finishes the
                    # prompt. num_cached starts block-aligned (prefix
                    # matches are whole blocks; the CoW case has a 1-token
                    # remainder and never reaches here), so aligned takes
                    # keep it aligned.
                    take = (take // self.allocator.block_size) * (
                        self.allocator.block_size
                    )
                    if take == 0:
                        break
                remaining -= take
            plans.append((seq, take))
        return plans

    def prefill_backlog_tokens(self) -> int:
        """Prompt tokens admitted or queued but not yet fed through a
        prefill program: the chunked-prefill backlog gauge. O(waiting +
        running), called once per engine step (lengths only — building
        prefill_ids would copy every waiting prompt per step)."""
        backlog = sum(
            len(s.request.prompt_ids) + len(s.generated)
            for s in self.waiting
        )
        backlog += sum(
            s.prefill_len - s.num_cached
            for s in self.running
            if s.prefilling
        )
        return backlog

    def _admit(self, seq: Sequence) -> bool:
        """Map `seq`'s block table: share the longest cached block-prefix
        of prefill_ids (refcount bumps) and allocate only the uncached
        tail. Returns False when the pool cannot hold the tail."""
        ids = seq.prefill_ids
        n = len(ids)
        bs = self.allocator.block_size
        total = blocks_for_tokens(n, bs)
        if not self.allocator.enable_prefix_caching:
            if not self.allocator.can_allocate(total):
                return False
            # ray-tpu: lint-ignore[RTL404] the free() below belongs to the
            # prefix-caching branch; this branch allocates (pre-checked
            # above, cannot raise) and returns with the blocks owned
            seq.block_table = self.allocator.allocate(total)
            seq.block_hashes = []
            seq.num_cached = 0
            seq.pending_restore = []
            return True
        hashes = prefix_block_hashes(ids, bs)
        matched = self.allocator.match_prefix(hashes)
        k = len(matched)
        # A fully-cached prompt still needs its last token's logits, and
        # that token's K/V write lands inside the last matched (shared,
        # immutable) block: copy-on-write it.
        cow = k > 0 and k * bs == n
        need = total - k + (1 if cow else 0)
        # KV fabric: extend the prefix match past the device cache into
        # the host tier. Restored blocks land in freshly allocated slots
        # (the leading blocks of `tail` below), capped so at least the
        # final token stays uncached — full fabric coverage would need the
        # CoW machinery against a block that doesn't exist on device yet,
        # and recomputing one trailing block is cheaper than growing a
        # second CoW path.
        f = 0
        if self.fabric_probe is not None and not cow:
            max_restorable = (n - 1) // bs
            if k < max_restorable:
                for hit in self.fabric_probe(hashes[k:max_restorable]):
                    if not hit:
                        break
                    f += 1
        # Shield the matched prefix from being evicted by the tail
        # allocation below (and from anyone else while this seq runs).
        # ray-tpu: lint-ignore[RTL404] nothing between touch and the
        # failure-path free can raise (can_allocate is a pure check and
        # allocate is pre-checked); the engine lock serializes callers
        self.allocator.touch(matched)
        if not self.allocator.can_allocate(need):
            self.allocator.free(matched)
            return False
        tail = self.allocator.allocate(need)
        seq.block_hashes = hashes[:k]
        seq.pending_restore = list(zip(tail[:f], hashes[k : k + f]))
        if cow:
            src, dst = matched[-1], tail[0]
            seq.block_table = matched[:-1] + [dst]
            # The engine device-copies src -> dst before the suffix prefill
            # runs; the extra ref taken on src above is dropped after the
            # copy (engine) or on release (abort in the same step).
            seq.pending_copy = (src, dst)
            seq.num_cached = n - 1
            self.num_cow_blocks += 1
        else:
            seq.block_table = matched + tail
            seq.num_cached = k * bs
        return True

    # ---------------- decode ----------------

    def schedule_decode(self) -> List[Sequence]:
        """Ensure every decode-ready running sequence owns a block for the
        position its next token will be written to; preempt the youngest
        sequences on cache pressure. Returns the decode batch — running
        sequences that are NOT still prefilling (a mid-chunk sequence holds
        its slot and blocks but must not decode from K/V that was never
        computed; admission already allocated its whole table, so it needs
        no block here either)."""
        for seq in list(self.running):
            if not seq.is_running:
                continue  # preempted by an earlier iteration of this loop
            if seq.prefilling:
                continue  # mid-chunk: no decode, no extra block needed
            needed = seq.num_cached // self.allocator.block_size + 1
            if needed > self.max_blocks_per_seq:
                raise RuntimeError(
                    f"sequence {seq.request.request_id} outgrew "
                    f"max_blocks_per_seq={self.max_blocks_per_seq}; the "
                    "engine must bound prompt+max_new_tokens at admission"
                )
            while len(seq.block_table) < needed:
                try:
                    seq.block_table.extend(self.allocator.allocate(1))
                except CacheOutOfBlocks:
                    # Evict the lowest-priority (youngest-arrival) running
                    # sequence — possibly the requester itself. Its keyed
                    # blocks stay cached-but-evictable, so its resume
                    # prefill is mostly hits unless pressure persists.
                    victim = max(self.running, key=lambda s: s.arrival)
                    self.preempt(victim)
                    if victim is seq:
                        break
        return [s for s in self.running if not s.prefilling]

    def reserve_decode_lookahead(self, seqs: List[Sequence]) -> bool:
        """Extend block tables so a CHAINED decode step can run before the
        in-flight step commits: the chained write lands at position
        num_cached + 1 (num_cached has not advanced yet — the in-flight
        token commits it later), needing (num_cached + 1) // bs + 1 blocks
        per sequence. Unlike schedule_decode this NEVER preempts — with a
        step in flight, preemption would reset a sequence whose uncommitted
        token is still on device — and never raises: on pool pressure, a
        per-sequence table cap, or a sequence whose chained write would
        fall past max_blocks_per_seq * bs, it allocates nothing and returns
        False so the engine flushes the pipeline and schedules normally.
        All-or-nothing: the batch chains together or not at all."""
        bs = self.allocator.block_size
        extras: List[Tuple[Sequence, int]] = []
        for seq in seqs:
            needed = (seq.num_cached + 1) // bs + 1
            if needed > self.max_blocks_per_seq:
                return False
            extras.append((seq, max(0, needed - len(seq.block_table))))
        total = sum(extra for _, extra in extras)
        if total and not self.allocator.can_allocate(total):
            return False
        for seq, extra in extras:
            if extra:
                seq.block_table.extend(self.allocator.allocate(extra))
        return True

    def reserve_speculative(self, seq: Sequence, num_tokens: int) -> int:
        """Extend `seq`'s block table so a verify step can write K/V for
        its next token PLUS up to `num_tokens` speculative tokens
        (positions num_cached .. num_cached + num_tokens). Speculation is
        opportunistic: it never preempts another sequence for blocks —
        on pool pressure (or the per-sequence block/length caps) the count
        is shrunk, down to 0 (plain decode). Returns the number of
        speculative tokens actually covered; the caller feeds exactly
        1 + that many tokens. Call after schedule_decode(), which already
        guaranteed the plain-decode block."""
        bs = self.allocator.block_size
        # Length cap: the furthest write lands at position
        # num_cached + num_tokens, which must stay inside the table.
        num_tokens = min(
            num_tokens, self.max_blocks_per_seq * bs - seq.num_cached - 1
        )
        while num_tokens > 0:
            extra = (
                blocks_for_tokens(seq.num_cached + 1 + num_tokens, bs)
                - len(seq.block_table)
            )
            if extra <= 0:
                return num_tokens
            if self.allocator.can_allocate(extra):
                seq.block_table.extend(self.allocator.allocate(extra))
                return num_tokens
            num_tokens -= 1
        return 0

    def rollback(self, seq: Sequence, num_cached: int) -> None:
        """Commit + roll back after a verify step: `num_cached` becomes the
        count of tokens whose K/V is valid in the cache (the accepted
        prefix of what the verify program scattered), and the speculative
        tail blocks past the committed region are freed. Rejected tokens'
        K/V stays in the kept blocks as garbage above num_cached — every
        attention masks positions >= context_len, and the next write
        overwrites it. Trimmed blocks were never published to the prefix
        cache (only full blocks at or below num_cached get chain keys), so
        they return to the plain free list."""
        covered = len(seq.block_table) * self.allocator.block_size
        if num_cached > covered:
            raise ValueError(
                f"rollback target {num_cached} exceeds the {covered} "
                "tokens this sequence's block table covers — the verify "
                "step cannot have written there"
            )
        seq.num_cached = num_cached
        keep = blocks_for_tokens(num_cached, self.allocator.block_size)
        if len(seq.block_table) > keep:
            tail = seq.block_table[keep:]
            del seq.block_table[keep:]
            self.allocator.free(tail)

    def preempt(self, seq: Sequence) -> None:
        """Recompute-style preemption: free the blocks, fold generated
        tokens into the prompt, and put the sequence at the front of the
        waiting queue so it resumes first."""
        self.running.remove(seq)
        seq.is_running = False
        self._release(seq)
        seq.num_preemptions += 1
        self.num_preemptions += 1
        self.waiting.appendleft(seq)
        if self.on_preempt is not None:
            self.on_preempt(seq)

    def finish(self, seq: Sequence, reason: str) -> None:
        self.running.remove(seq)
        seq.is_running = False
        self._release(seq)
        self._active.pop(seq.request.request_id, None)
        seq.finish_reason = reason

    # ---------------- prefix-cache bookkeeping ----------------

    def note_filled_blocks(self, seq: Sequence) -> None:
        """Publish every newly-filled full block of `seq` under its chain
        key so later admissions (including this sequence's own resume after
        a preemption) can share it. Idempotent; call after prefill and
        whenever decode fills a block."""
        if not self.allocator.enable_prefix_caching:
            return
        bs = self.allocator.block_size
        full = seq.num_cached // bs
        if len(seq.block_hashes) >= full:
            return
        stream = seq.request.prompt_ids + seq.generated
        while len(seq.block_hashes) < full:
            j = len(seq.block_hashes)
            prev = seq.block_hashes[-1] if seq.block_hashes else None
            h = hash_block_tokens(prev, stream[j * bs : (j + 1) * bs])
            seq.block_hashes.append(h)
            self.allocator.register(seq.block_table[j], h)

    def _release(self, seq: Sequence) -> None:
        if seq.pending_copy is not None:
            # Admission holds one extra ref on the copy source until the
            # engine performs the device copy; a release before that must
            # drop it too.
            self.allocator.free([seq.pending_copy[0]])
            seq.pending_copy = None
        if seq.block_table:
            self.allocator.free(seq.block_table)
        seq.block_table = []
        seq.block_hashes = []
        seq.num_cached = 0
        seq.pending_restore = []
