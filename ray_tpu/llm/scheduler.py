"""Iteration-level (continuous batching) scheduler.

Every engine step: admit queued prompts into free decode slots while the
cache has room, continue every running sequence by one token, and preempt
under cache pressure. Preemption is recompute-style: the victim's blocks
are freed and it re-enters the front of the waiting queue with its
already-generated tokens folded into the prompt, so a later prefill
restores its state exactly (tokens already streamed out are not re-emitted
— `emitted` survives preemption).
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Deque, List, Optional

from ray_tpu.llm.cache import BlockAllocator, CacheOutOfBlocks, blocks_for_tokens


FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_ABORTED = "aborted"

_arrival = itertools.count()


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_ids: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None


class Sequence:
    """One request's in-flight state."""

    def __init__(self, request: Request):
        self.request = request
        self.generated: List[int] = []
        self.block_table: List[int] = []
        self.num_cached = 0  # tokens whose K/V sit in the paged cache
        self.emitted = 0  # generated tokens already streamed to the caller
        self.arrival = next(_arrival)
        self.finish_reason: Optional[str] = None
        self.num_preemptions = 0

    @property
    def prefill_ids(self) -> List[int]:
        # After a preemption the generated suffix is recomputed as prompt.
        return self.request.prompt_ids + self.generated

    @property
    def last_token(self) -> int:
        return self.generated[-1] if self.generated else self.request.prompt_ids[-1]

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None


class Scheduler:
    def __init__(
        self,
        allocator: BlockAllocator,
        max_decode_slots: int,
        max_blocks_per_seq: int,
    ):
        self.allocator = allocator
        self.max_decode_slots = max_decode_slots
        self.max_blocks_per_seq = max_blocks_per_seq
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []  # arrival order
        self.num_preemptions = 0

    # ---------------- queue management ----------------

    def add(self, seq: Sequence) -> None:
        self.waiting.append(seq)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def abort(self, request_id: str) -> Optional[Sequence]:
        for i, seq in enumerate(self.running):
            if seq.request.request_id == request_id:
                self.running.pop(i)
                self._release(seq)
                seq.finish_reason = FINISH_ABORTED
                return seq
        for i, seq in enumerate(self.waiting):
            if seq.request.request_id == request_id:
                del self.waiting[i]
                seq.finish_reason = FINISH_ABORTED
                return seq
        return None

    # ---------------- admission (prefill) ----------------

    def schedule_prefills(self, max_prefills: int) -> List[Sequence]:
        """Admit waiting sequences into free slots, FIFO, while the cache
        can hold their full prompt (plus-generated, after preemption)."""
        admitted: List[Sequence] = []
        while (
            self.waiting
            and len(self.running) < self.max_decode_slots
            and len(admitted) < max_prefills
        ):
            seq = self.waiting[0]
            need = blocks_for_tokens(
                len(seq.prefill_ids), self.allocator.block_size
            )
            if not self.allocator.can_allocate(need):
                break  # head-of-line blocking is deliberate: FIFO fairness
            self.waiting.popleft()
            seq.block_table = self.allocator.allocate(need)
            admitted.append(seq)
            self.running.append(seq)
        return admitted

    # ---------------- decode ----------------

    def schedule_decode(self) -> List[Sequence]:
        """Ensure every running sequence owns a block for the position its
        next token will be written to; preempt the youngest sequences on
        cache pressure. Returns the surviving running list."""
        survivors: List[Sequence] = []
        for seq in list(self.running):
            if seq not in self.running:
                continue  # preempted by an earlier iteration of this loop
            needed = seq.num_cached // self.allocator.block_size + 1
            if needed > self.max_blocks_per_seq:
                raise RuntimeError(
                    f"sequence {seq.request.request_id} outgrew "
                    f"max_blocks_per_seq={self.max_blocks_per_seq}; the "
                    "engine must bound prompt+max_new_tokens at admission"
                )
            while len(seq.block_table) < needed:
                try:
                    seq.block_table.extend(self.allocator.allocate(1))
                except CacheOutOfBlocks:
                    # Evict the lowest-priority (youngest-arrival) running
                    # sequence — possibly the requester itself.
                    victim = max(self.running, key=lambda s: s.arrival)
                    self.preempt(victim)
                    if victim in survivors:
                        survivors.remove(victim)
                    if victim is seq:
                        break
            else:
                survivors.append(seq)
        return survivors

    def preempt(self, seq: Sequence) -> None:
        """Recompute-style preemption: free the blocks, fold generated
        tokens into the prompt, and put the sequence at the front of the
        waiting queue so it resumes first."""
        self.running.remove(seq)
        self._release(seq)
        seq.num_preemptions += 1
        self.num_preemptions += 1
        self.waiting.appendleft(seq)

    def finish(self, seq: Sequence, reason: str) -> None:
        self.running.remove(seq)
        self._release(seq)
        seq.finish_reason = reason

    def _release(self, seq: Sequence) -> None:
        if seq.block_table:
            self.allocator.free(seq.block_table)
        seq.block_table = []
        seq.num_cached = 0
