"""Per-request serving observability: lifecycle spans + flight recorder.

Two pieces the engine hooks into (gated by EngineConfig.instrument):

  * RequestTrace — one per in-flight request. The trace context is captured
    once at submission (the LLMServer.generate actor-task span, which chains
    back through the replica task to the Serve handle caller), and every
    lifecycle phase — queue wait, prefill (full/partial/CoW), decode
    stretches, preemption + resume, terminal state — is emitted as a span
    against it from the engine loop thread via tracing.emit_span, so a
    streamed request yields one connected trace in tracing.traces().
    Decode is recorded per STRETCH (admission → preempt/finish), never per
    token: the hot loop only bumps plain floats at step boundaries.

  * FlightRecorder — a bounded ring of structured per-step records (step
    index, phase, batch size, tokens in/out, buckets, prefix-cache hits,
    preemptions, duration; with speculative decoding on, verify steps add
    a "speculation" record — proposer mode, fed bucket, proposed /
    accepted / emitted counts) plus warmup compile events (cold-compile
    blame) and step failures from the PR 3 poison-isolation path. Exposed
    through LLMServer.flight_record() and the dashboard /api/llm panel.

The request latency histograms live here too so every engine shares one
registered metric per name (vLLM reports the same trio — TTFT, time per
output token, e2e — as the primary serving SLO metrics).
"""

from __future__ import annotations

import time
import uuid
from collections import deque
from typing import List, Optional

from ray_tpu.util import tracing

# Bucket rationale: requests cover ~1 ms (cache-hit prefill of a short
# prompt on warm programs) to minutes (long decode under preemption), so
# request-level histograms use a 1-2.5-5 decade ladder across ms → minute.
REQUEST_SECONDS_BOUNDARIES = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
]
# Per-output-token latency: decode steps are ~100 µs – 100 ms per token
# depending on batch width and hardware; the ladder starts a decade lower.
PER_TOKEN_SECONDS_BOUNDARIES = [
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0,
]
# One engine step (a single jitted program dispatch + host bookkeeping).
STEP_SECONDS_BOUNDARIES = [
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5,
]
# Host gap between consecutive decode dispatches: how long the device sat
# idle waiting on host scheduling/commit work before the next program was
# queued. This is the number async_scheduling exists to shrink — a chained
# dispatch issued before the previous step's results were even fetched
# records 0, so the ladder starts at 10 µs and the first bucket is the
# "pipelined" bucket.
HOST_GAP_SECONDS_BOUNDARIES = [
    0.00001, 0.000025, 0.00005, 0.0001, 0.00025, 0.0005, 0.001,
    0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
]


class RequestTrace:
    """Phase-span emitter for one request; all mutation happens at phase
    boundaries (admission, prefill end, preemption, finish) — zero work in
    the per-token decode path."""

    __slots__ = (
        "request_id",
        "trace_id",
        "parent_span_id",
        "root_span_id",
        "submit_s",
        "queue_start",
        "queue_waits",
        "first_token_s",
        "stretch_start",
        "stretch_base",
        "prefills",
        "preempts",
        "error",
    )

    def __init__(self, request_id: str, parent_ctx: Optional[tuple]):
        self.request_id = request_id
        if parent_ctx is not None:
            self.trace_id, self.parent_span_id = parent_ctx
        else:
            self.trace_id = uuid.uuid4().hex[:16]
            self.parent_span_id = None
        self.root_span_id = tracing.new_span_id()
        now = time.time()
        self.submit_s = now
        self.queue_start: Optional[float] = now  # in queue from submission
        self.queue_waits = 0
        self.first_token_s: Optional[float] = None
        self.stretch_start: Optional[float] = None
        self.stretch_base = 0  # generated-token count when the stretch began
        self.prefills = 0
        self.preempts = 0
        self.error: Optional[str] = None

    def _emit(self, name, start_s, end_s, attributes=None) -> None:
        tracing.emit_span(
            name,
            start_s,
            end_s,
            trace_id=self.trace_id,
            parent_span_id=self.root_span_id,
            attributes=attributes,
        )

    def on_admitted(self, now: float) -> float:
        """Close the current queue-wait span; returns the wait in seconds
        (initial admission and every preempt-resume each count one wait)."""
        start = self.queue_start if self.queue_start is not None else now
        self.queue_start = None
        self.queue_waits += 1
        self._emit(
            "llm.queue", start, now, {"wait": self.queue_waits - 1}
        )
        return now - start

    def on_prefilled(
        self, start_s: float, now: float, kind: str, bucket: int,
        n_tokens: int, cached_tokens: int, n_generated: int,
        chunk: int = 0, final: bool = True,
    ) -> None:
        """One prefill program ran for this request (kind: full | partial |
        cow; `chunk` indexes the dispatch within the current admission
        under chunked prefill). Only the FINAL chunk produces a token, so
        only it sets first-token time and opens a decode stretch: tokens
        generated from here to the next preempt/finish belong to it (the
        prefill's own first token is attributed to the prefill span, not
        the stretch). Continuation chunks just record their span — TTFT
        keeps exactly one observation per request either way."""
        self.prefills += 1
        self._emit(
            "llm.prefill",
            start_s,
            now,
            {
                "kind": kind,
                "bucket": bucket,
                "tokens": n_tokens,
                "cached_tokens": cached_tokens,
                "chunk": chunk,
                "final": final,
            },
        )
        if not final:
            return
        if self.first_token_s is None:
            self.first_token_s = now
        self.stretch_start = now
        self.stretch_base = n_generated

    def _close_stretch(self, now: float, n_generated: int) -> None:
        if self.stretch_start is None:
            return
        tokens = n_generated - self.stretch_base
        if tokens > 0:
            self._emit(
                "llm.decode", self.stretch_start, now, {"tokens": tokens}
            )
        self.stretch_start = None
        self.stretch_base = n_generated

    def on_preempt(self, now: float, n_generated: int) -> None:
        """Recompute-style preemption: close the decode stretch, mark the
        event, and re-enter the queue (the resume prefill reopens it)."""
        self._close_stretch(now, n_generated)
        self.preempts += 1
        self._emit("llm.preempt", now, now, {"preemption": self.preempts})
        self.queue_start = now

    def on_finish(self, now: float, seq) -> None:
        """Terminal state: close any open stretch and the request root span.
        Dead-lettered requests (finish_reason="error") close with error
        status and the step exception that killed them."""
        self._close_stretch(now, len(seq.generated))
        attrs = {
            "request_id": self.request_id,
            "prompt_tokens": len(seq.request.prompt_ids),
            "generated_tokens": len(seq.generated),
            "finish_reason": seq.finish_reason,
            "preemptions": self.preempts,
            "prefills": self.prefills,
            "status": "error" if self.error is not None else "ok",
        }
        if self.first_token_s is not None:
            attrs["ttft_s"] = self.first_token_s - self.submit_s
        if self.error is not None:
            attrs["error"] = self.error
        tracing.emit_span(
            "llm.request",
            self.submit_s,
            now,
            trace_id=self.trace_id,
            parent_span_id=self.parent_span_id,
            span_id=self.root_span_id,
            attributes=attrs,
        )


class FlightRecorder:
    """Bounded rings of what the engine loop actually did.

    Writers are the engine step path (serialized by LLMServer's lock or the
    caller's single thread); deque appends are atomic, so readers snapshot
    safely from any thread. Failures are recorded even with instrumentation
    off — a crashed step must always leave a trace."""

    def __init__(self, capacity: int = 256):
        self.steps: deque = deque(maxlen=capacity)
        self.compile_events: deque = deque(maxlen=128)
        self.failures: deque = deque(maxlen=128)
        # Overload plane: bounded-admission rejections and deadline
        # expiries. Recorded even with instrumentation off, like
        # failures — shed/expired traffic is precisely the traffic an
        # operator will be asked to explain after the fact.
        self.sheds: deque = deque(maxlen=128)
        self.expiries: deque = deque(maxlen=128)

    def record_step(self, record: dict) -> None:
        self.steps.append(record)

    def record_compile(
        self, program: str, bucket: int, seconds: float
    ) -> None:
        """Warmup compile blame: which program/bucket cost how many cold
        seconds before the engine reported ready."""
        self.compile_events.append(
            {
                "program": program,
                "bucket": bucket,
                "compile_s": round(seconds, 6),
                "time": time.time(),
            }
        )

    def record_failure(
        self,
        step: int,
        error: str,
        request_id: Optional[str] = None,
        action: str = "retry",
    ) -> None:
        """One failed engine step and what the loop did about it:
        "dead_letter" (poison isolation), "retry" (unattributable,
        below threshold), or "wedged" (threshold tripped)."""
        self.failures.append(
            {
                "step": step,
                "error": error,
                "request_id": request_id,
                "action": action,
                "time": time.time(),
            }
        )

    def record_shed(
        self,
        request_id: Optional[str],
        reason: str,
        queue_len: int,
        step: int,
    ) -> None:
        """One submission rejected by bounded admission (or dead on
        arrival): why, and how deep the backlog stood when it was shed."""
        self.sheds.append(
            {
                "request_id": request_id,
                "reason": reason,
                "queue_len": queue_len,
                "step": step,
                "time": time.time(),
            }
        )

    def record_expiry(
        self,
        request_id: str,
        phase: str,
        step: int,
        tokens_generated: int,
    ) -> None:
        """One admitted request dropped at its deadline: "queued" means it
        never cost a prefill program; "running" means it was aborted
        mid-stream with its blocks reclaimed this step."""
        self.expiries.append(
            {
                "request_id": request_id,
                "phase": phase,
                "step": step,
                "tokens_generated": tokens_generated,
                "time": time.time(),
            }
        )

    def snapshot(self, steps_limit: Optional[int] = None) -> dict:
        steps: List[dict] = list(self.steps)
        if steps_limit is not None and steps_limit >= 0:
            # NOT steps[-steps_limit:]: a 0 limit must mean zero records,
            # but [-0:] slices the whole list.
            steps = (
                steps[max(len(steps) - steps_limit, 0) :]
                if steps_limit
                else []
            )
        return {
            "steps": steps,
            "compile_events": list(self.compile_events),
            "failures": list(self.failures),
            "sheds": list(self.sheds),
            "expiries": list(self.expiries),
        }
