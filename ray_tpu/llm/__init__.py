"""ray_tpu.llm — continuous-batching LLM inference on a paged KV cache.

Pure-Python library on the actor/object core (the Ray layering principle):
  * cache.py — refcounted, content-addressed block allocator over the
    preallocated paged KV pools (automatic prefix caching)
  * model_runner.py — O(1) jitted prefill/partial-prefill/decode programs
    for the GPT model
  * scheduler.py — iteration-level prefix-aware admission, continuation,
    preemption
  * engine.py — LLMEngine core + LLMServer engine actor
  * observability.py — per-request lifecycle spans, latency-histogram
    boundaries, and the engine flight recorder
  * spec/ — speculative decoding proposers (n-gram prompt lookup, draft
    model) feeding the engine's k-token verify-with-rollback phase
  * serve.py — ingress deployment behind the existing HTTP proxy/replicas
  * kvfabric/ — fleet-wide KV fabric: host-DRAM spill tier shared across
    engines, disaggregated prefill/decode roles, prefix-affinity routing
"""

from ray_tpu.llm.cache import (
    EVICTION_POLICIES,
    NULL_BLOCK,
    BlockAllocator,
    CacheOutOfBlocks,
    blocks_for_tokens,
    hash_block_tokens,
    prefix_block_hashes,
)
from ray_tpu.llm.config import EngineConfig, KVFabricConfig
from ray_tpu.llm.engine import LLMEngine, LLMServer
from ray_tpu.llm.model_runner import GPTRunner
from ray_tpu.llm.scheduler import (
    FINISH_ABORTED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    Request,
    Scheduler,
    Sequence,
)
from ray_tpu.llm.spec import NgramProposer, Proposer, build_proposer

__all__ = [
    "BlockAllocator",
    "CacheOutOfBlocks",
    "EVICTION_POLICIES",
    "EngineConfig",
    "FINISH_ABORTED",
    "FINISH_EOS",
    "FINISH_ERROR",
    "FINISH_LENGTH",
    "GPTRunner",
    "KVFabricConfig",
    "LLMEngine",
    "LLMServer",
    "NULL_BLOCK",
    "NgramProposer",
    "Proposer",
    "Request",
    "Scheduler",
    "Sequence",
    "blocks_for_tokens",
    "build_proposer",
    "hash_block_tokens",
    "prefix_block_hashes",
]
