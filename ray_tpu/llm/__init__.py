"""ray_tpu.llm — continuous-batching LLM inference on a paged KV cache.

Pure-Python library on the actor/object core (the Ray layering principle):
  * cache.py — block allocator over the preallocated paged KV pools
  * model_runner.py — O(1) jitted prefill/decode programs for the GPT model
  * scheduler.py — iteration-level admission, continuation, preemption
  * engine.py — LLMEngine core + LLMServer engine actor
  * serve.py — ingress deployment behind the existing HTTP proxy/replicas
"""

from ray_tpu.llm.cache import (
    NULL_BLOCK,
    BlockAllocator,
    CacheOutOfBlocks,
    blocks_for_tokens,
)
from ray_tpu.llm.config import EngineConfig
from ray_tpu.llm.engine import LLMEngine, LLMServer
from ray_tpu.llm.model_runner import GPTRunner
from ray_tpu.llm.scheduler import (
    FINISH_ABORTED,
    FINISH_EOS,
    FINISH_LENGTH,
    Request,
    Scheduler,
    Sequence,
)

__all__ = [
    "BlockAllocator",
    "CacheOutOfBlocks",
    "EngineConfig",
    "FINISH_ABORTED",
    "FINISH_EOS",
    "FINISH_LENGTH",
    "GPTRunner",
    "LLMEngine",
    "LLMServer",
    "NULL_BLOCK",
    "Request",
    "Scheduler",
    "Sequence",
    "blocks_for_tokens",
]
