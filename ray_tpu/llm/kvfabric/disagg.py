"""Disaggregated prefill/decode over the KV fabric.

Two engine actors with one fabric between them: the prefill-role engine
(`EngineConfig.engine_role="prefill"`) runs chunked prefill only —
publishing every finished KV block to the fabric as its chunk completes
and finishing the request at its first token — and the decode-role
engine admits the handed-off request as a pure fabric hit, restoring the
published blocks into its own pool and generating the rest. The handoff
is actors + object refs end to end: the prefill reply ref gates the
decode submission, and the KV bytes move through the fabric store, not
through any new jitted program shape.

Greedy outputs are token-identical to a unified engine: the decode
engine's admission restores every full prefix block (cache-hit tokens),
suffix-prefills the trailing partial block, and its first generated
token reproduces the prefill engine's — the same contract as a local
prefix-cache hit.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import ray_tpu
from ray_tpu.llm.config import EngineConfig
from ray_tpu.llm.engine import LLMServer


class DisaggregatedLLM:
    """A prefill-role + decode-role engine pair sharing one fabric.

    `engine_config` must name a kv_fabric; its engine_role is overridden
    per member ("prefill" additionally forces chunked prefill on when the
    caller left it off, since the prefill role requires it)."""

    def __init__(
        self,
        model_config=None,
        engine_config: Optional[EngineConfig] = None,
        params=None,
        seed: int = 0,
        name: str = "disagg",
        max_concurrency: int = 8,
    ):
        engine_config = engine_config or EngineConfig()
        if engine_config.kv_fabric is None:
            raise ValueError(
                "DisaggregatedLLM requires engine_config.kv_fabric — the "
                "fabric is the only channel prefilled KV blocks travel "
                "from the prefill engine to the decode engine"
            )
        prefill_cfg = dataclasses.replace(
            engine_config,
            engine_role="prefill",
            max_prefill_tokens_per_step=(
                engine_config.max_prefill_tokens_per_step
                if engine_config.prefill_token_budget is not None
                else -1
            ),
        )
        decode_cfg = dataclasses.replace(engine_config, engine_role="decode")

        def _engine(suffix: str, cfg: EngineConfig):
            return (
                ray_tpu.remote(LLMServer)
                .options(
                    name=f"llm_engine:{name}-{suffix}",
                    get_if_exists=True,
                    max_concurrency=max_concurrency,
                )
                .remote(model_config, cfg, params, seed)
            )

        self._prefill = _engine("prefill", prefill_cfg)
        self._decode = _engine("decode", decode_cfg)

    def generate(
        self,
        prompt_ids: List[int],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
    ) -> List[int]:
        """Prefill on one engine, decode on the other; returns the decode
        engine's generated token ids (the full generation — the prefill
        engine's single first token is subsumed by it)."""
        # The handoff: the prefill reply ref is the barrier — its KV
        # blocks are published to the fabric before the reply seals, so
        # the decode admission that follows sees them as fabric hits.
        ray_tpu.get(self._prefill.generate.remote(prompt_ids, 1, eos_id))
        return ray_tpu.get(
            self._decode.generate.remote(prompt_ids, max_new_tokens, eos_id)
        )

    def prefill_stats(self) -> dict:
        return ray_tpu.get(self._prefill.metrics.remote())

    def decode_stats(self) -> dict:
        return ray_tpu.get(self._decode.metrics.remote())

    def shutdown(self) -> None:
        for handle in (self._prefill, self._decode):
            try:
                ray_tpu.get(handle.shutdown.remote(), timeout=10.0)
            except Exception:
                pass
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
