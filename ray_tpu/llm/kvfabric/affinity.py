"""Prefix-affinity routing for LLM requests.

The router's replica pick consults a consistent hash on the prompt's
LEADING block-chain hash (llm.cache.hash_block_tokens over the first
block_size tokens): two requests sharing a first block — multi-turn
sessions over one system prefix, repeated prompts — map to the same
preferred replica, so they land where their KV cache already lives.

Affinity is a TIE-BREAK layered on the router's power-of-two-choices:
excluded/draining replicas are filtered before the preference is
consulted, and a preferred replica without capacity falls back to p2c —
affinity never overrides drain, exclusion, or health.

Rendezvous (highest-random-weight) hashing keeps the mapping consistent:
a replica joining or leaving remaps only the keys that scored highest on
it, not the whole space — exactly the property a drain needs so the
surviving replicas' affinities stay put.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ray_tpu.llm.cache import hash_block_tokens
from ray_tpu.util.consistent_hash import rendezvous_pick

__all__ = ["LLMPrefixAffinity", "leading_block_hash", "rendezvous_pick"]


def leading_block_hash(
    prompt_ids: Sequence[int], block_size: int
) -> Optional[int]:
    """Chain hash of the prompt's first full block — the affinity key.
    None for prompts shorter than one block (no shareable prefix: let
    plain p2c place them)."""
    if len(prompt_ids) < block_size:
        return None
    return hash_block_tokens(None, list(prompt_ids[:block_size]))


class LLMPrefixAffinity:
    """Picklable affinity-key extractor for LLMIngress request dicts —
    declared on the deployment (DeploymentConfig.affinity_key_fn) like
    stream_resume_fn, so every handle built from the app's config routes
    with prefix affinity. Returns the leading block-chain hash, or None
    (no affinity) for malformed/short prompts."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)

    def __call__(self, args: tuple, kwargs: dict) -> Optional[int]:
        if not args or not isinstance(args[0], dict):
            return None
        prompt_ids = args[0].get("prompt_ids")
        if not prompt_ids:
            return None
        try:
            return leading_block_hash(prompt_ids, self.block_size)
        except Exception:
            return None

    def __eq__(self, other):
        return (
            type(other) is LLMPrefixAffinity
            and other.block_size == self.block_size
        )

    def __repr__(self):
        return f"LLMPrefixAffinity(block_size={self.block_size})"
