"""KV fabric store: host-DRAM tier for spilled KV blocks, as an actor.

One named `KVFabricStore` actor per fabric (`kv_fabric:{name}`) holds the
device content of demoted blocks — K/V values plus int8 scales, as numpy
arrays — keyed by the block's content chain hash (llm.cache
hash_block_tokens). Chain hashes identify whole prefixes, so any engine
on the fabric can restore a hit into its own freshly allocated slot and
trust the content: the fleet shares one logical prefix cache.

The store is bounded by a byte budget with its own LRU: a spill that
would overflow evicts the least-recently-hit entries first, and an entry
larger than the whole budget is refused outright. Pure numpy + stdlib —
the actor never touches jax, so it costs no device memory and survives
any engine's death.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError


def payload_nbytes(payload: dict) -> int:
    """Total bytes of one block payload's arrays (None entries free)."""
    return sum(
        a.nbytes for a in payload.values() if hasattr(a, "nbytes")
    )


class KVFabricStore:
    """Byte-budgeted LRU of block payloads keyed by chain hash."""

    def __init__(self, byte_budget: int):
        if byte_budget < 1:
            raise ValueError(
                f"fabric byte_budget must be >= 1, got {byte_budget}"
            )
        self._budget = int(byte_budget)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[int, dict]" = OrderedDict()
        self._bytes: Dict[int, int] = {}
        self._bytes_used = 0
        self._hits = 0
        self._misses = 0
        self._puts = 0
        self._evictions = 0

    def put(self, block_hash: int, payload: dict) -> bool:
        """Insert one block payload; True when it is resident afterwards.
        An already-present hash refreshes recency without rewriting (the
        content is immutable — equal chain hashes mean equal prefixes).
        Payloads larger than the whole budget are refused."""
        nbytes = payload_nbytes(payload)
        with self._lock:
            if block_hash in self._entries:
                self._entries.move_to_end(block_hash)
                return True
            if nbytes > self._budget:
                return False
            while self._bytes_used + nbytes > self._budget:
                old_hash, _ = self._entries.popitem(last=False)
                self._bytes_used -= self._bytes.pop(old_hash)
                self._evictions += 1
            self._entries[block_hash] = payload
            self._bytes[block_hash] = nbytes
            self._bytes_used += nbytes
            self._puts += 1
            return True

    def put_many(self, items: List[tuple]) -> int:
        """Batch put of [(block_hash, payload), ...]; returns how many are
        resident afterwards — one RPC for a drain flush."""
        return sum(1 for h, p in items if self.put(h, p))

    def get(self, block_hash: int) -> Optional[dict]:
        with self._lock:
            payload = self._entries.get(block_hash)
            if payload is None:
                self._misses += 1
                return None
            self._entries.move_to_end(block_hash)
            self._hits += 1
            return payload

    def get_many(self, block_hashes: List[int]) -> List[Optional[dict]]:
        """Batch get, one RPC for a whole restore chain. Order-preserving;
        misses are None."""
        return [self.get(h) for h in block_hashes]

    def contains(self, block_hashes: List[int]) -> List[bool]:
        """Batch membership, WITHOUT touching recency or hit counters —
        admission probes contains() first and only a restore that actually
        reads content should count as a hit."""
        with self._lock:
            return [h in self._entries for h in block_hashes]

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_blocks": len(self._entries),
                "bytes_used": self._bytes_used,
                "byte_budget": self._budget,
                "hits": self._hits,
                "misses": self._misses,
                "puts": self._puts,
                "evictions": self._evictions,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes.clear()
            self._bytes_used = 0

    def ping(self) -> str:
        return "pong"


def get_or_create_fabric_actor(name: str, byte_budget: int):
    """The fabric's shared store actor, named `kv_fabric:{name}` so every
    engine (and every ingress replica's engine) on the same fabric name
    rendezvouses on one store. First creation pins the byte budget."""
    return (
        ray_tpu.remote(KVFabricStore)
        .options(
            name=f"kv_fabric:{name}",
            get_if_exists=True,
            max_concurrency=16,
        )
        .remote(byte_budget)
    )


class KVFabricClient:
    """Engine-side client: thin, synchronous wrapper over the store actor.

    Every method degrades to a miss/no-op when the store actor is gone
    (fleet teardown racing an engine's last steps) — the fabric is an
    accelerator, never a correctness dependency. Every RPC is bounded by
    `rpc_timeout_s` (put_many gets 6x — it moves a whole drain flush in
    one call), so a HUNG store actor stalls the engine no longer than a
    dead one; a timeout degrades to the same miss/no-op but additionally
    fires `on_timeout`, which the engine wires to the
    llm_engine_fabric_timeouts counter — "store is slow" and "store is
    cold" must be distinguishable on a dashboard."""

    def __init__(
        self,
        name: str,
        byte_budget: int,
        rpc_timeout_s: float = 5.0,
        on_timeout: Optional[Callable[[], None]] = None,
    ):
        self.name = name
        self._timeout = float(rpc_timeout_s)
        self._bulk_timeout = 6.0 * self._timeout
        self._on_timeout = on_timeout
        self.num_timeouts = 0
        self._actor = get_or_create_fabric_actor(name, byte_budget)

    def _note_timeout(self) -> None:
        self.num_timeouts += 1
        if self._on_timeout is not None:
            try:
                self._on_timeout()
            except Exception:
                pass  # a counter hook must never break the degrade path

    def put(self, block_hash: int, payload: dict) -> bool:
        try:
            return bool(
                ray_tpu.get(
                    self._actor.put.remote(block_hash, payload),
                    timeout=self._timeout,
                )
            )
        except GetTimeoutError:
            self._note_timeout()
            return False
        except Exception:
            return False

    def put_many(self, items: List[tuple]) -> int:
        if not items:
            return 0
        try:
            return int(
                ray_tpu.get(
                    self._actor.put_many.remote(items),
                    timeout=self._bulk_timeout,
                )
            )
        except GetTimeoutError:
            self._note_timeout()
            return 0
        except Exception:
            return 0

    def get_many(self, block_hashes: List[int]) -> List[Optional[dict]]:
        try:
            return ray_tpu.get(
                self._actor.get_many.remote(list(block_hashes)),
                timeout=self._timeout,
            )
        except GetTimeoutError:
            self._note_timeout()
            return [None] * len(block_hashes)
        except Exception:
            return [None] * len(block_hashes)

    def contains(self, block_hashes: List[int]) -> List[bool]:
        if not block_hashes:
            return []
        try:
            return ray_tpu.get(
                self._actor.contains.remote(list(block_hashes)),
                timeout=self._timeout,
            )
        except GetTimeoutError:
            self._note_timeout()
            return [False] * len(block_hashes)
        except Exception:
            return [False] * len(block_hashes)

    def stats(self) -> dict:
        try:
            return ray_tpu.get(
                self._actor.stats.remote(), timeout=self._timeout
            )
        except GetTimeoutError:
            self._note_timeout()
            return {}
        except Exception:
            return {}
