"""Fleet-wide KV fabric: KV blocks as first-class fleet objects.

Three composing pieces over the content-addressed paged cache:

  * **Spill tier** (`store.KVFabricStore` / `KVFabricClient`): eviction
    and drain demote keyed blocks to a shared host-DRAM store keyed by
    chain hash instead of destroying them; admission extends the prefix
    match past the device cache into the fabric and restores hits into
    freshly allocated slots.
  * **Disaggregated prefill/decode** (`disagg.DisaggregatedLLM` +
    `EngineConfig.engine_role`): a prefill-role engine publishes each
    finished block and hands off; a decode-role engine admits the
    handoff as a pure fabric hit.
  * **Prefix-affinity routing** (`affinity`): the serve router's replica
    pick consults a rendezvous hash on the prompt's leading block-chain
    hash, as a tie-break layered on p2c.

Everything is gated on `EngineConfig.kv_fabric` (default off): with the
knob unset, no fabric actor exists and every existing path is untouched.
"""

from ray_tpu.llm.config import KVFabricConfig
from ray_tpu.llm.kvfabric.affinity import (
    LLMPrefixAffinity,
    leading_block_hash,
    rendezvous_pick,
)
from ray_tpu.llm.kvfabric.disagg import DisaggregatedLLM
from ray_tpu.llm.kvfabric.store import (
    KVFabricClient,
    KVFabricStore,
    get_or_create_fabric_actor,
    payload_nbytes,
)

__all__ = [
    "KVFabricConfig",
    "KVFabricClient",
    "KVFabricStore",
    "DisaggregatedLLM",
    "LLMPrefixAffinity",
    "get_or_create_fabric_actor",
    "leading_block_hash",
    "payload_nbytes",
    "rendezvous_pick",
]
