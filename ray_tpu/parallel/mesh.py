"""Device mesh formation.

TPU-native replacement for the reference's process-group bootstrap
(train/torch/config.py:69 _setup_torch_process_group + util/collective NCCL
rendezvous): on TPU the framework's job is *mesh formation* — pick axis sizes,
build a `jax.sharding.Mesh` over the slice's devices, and hand out shardings;
the collectives themselves are emitted by XLA over ICI (SURVEY.md §2.5).

Axis convention (orders matter: outermost→innermost = slowest→fastest varying,
so axes that should ride ICI neighbors go last):

    pp    — pipeline parallelism (microbatch p2p only; tolerates DCN)
    dp    — pure data parallel (replicated params)
    fsdp  — data parallel with sharded params/optimizer (ZeRO-3 analog)
    sp    — sequence/context parallelism (ring attention neighbors)
    tp    — tensor parallelism (megatron-style sharded matmuls)
    ep    — expert parallelism (MoE)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. Axis size -1 means 'absorb remaining devices'
    (at most one axis may be -1); absent axes are size 1.

    pp is outermost: pipeline stages exchange only microbatch activations
    (point-to-point), so they tolerate the slowest links — across slices the
    pp axis rides DCN while the inner axes stay on ICI."""

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def axis_sizes(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.axis_sizes()
        wildcards = [k for k, v in sizes.items() if v == -1]
        if len(wildcards) > 1:
            raise ValueError(f"At most one -1 axis allowed, got {wildcards}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wildcards:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"Mesh axes {sizes} require {fixed} devices but {n_devices} present"
            )
        return MeshSpec(**sizes)

    def active_axes(self) -> list[str]:
        return [name for name in AXIS_ORDER if getattr(self, name) > 1]

    def build(self, devices: Optional[Sequence] = None):
        """Create the `jax.sharding.Mesh`. All six axes are always present
        (size-1 axes are free), so sharding rules can name any axis."""
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        spec = self.resolve(len(devices))
        sizes = spec.axis_sizes()
        dev_array = np.asarray(devices).reshape([sizes[a] for a in AXIS_ORDER])
        return Mesh(dev_array, AXIS_ORDER)


def auto_mesh(
    n_devices: int,
    *,
    strategy: str = "dp",
    tp: int = 1,
    sp: int = 1,
) -> MeshSpec:
    """Heuristic mesh shapes for common strategies.

    strategy: "dp" (replicated), "fsdp" (sharded params), "tp+fsdp", "sp+fsdp".
    """
    if strategy == "dp":
        return MeshSpec(dp=-1).resolve(n_devices)
    if strategy == "fsdp":
        return MeshSpec(fsdp=-1).resolve(n_devices)
    if strategy == "tp+fsdp":
        return MeshSpec(fsdp=-1, tp=tp).resolve(n_devices)
    if strategy == "sp+fsdp":
        return MeshSpec(fsdp=-1, sp=sp).resolve(n_devices)
    raise ValueError(f"Unknown mesh strategy {strategy!r}")


def tensor_parallel_mesh(tensor_parallel_size: int, devices=None):
    """The LLM serving engine's intra-replica mesh: `tp` over the first
    `tensor_parallel_size` backend devices, every other axis size 1.

    Fails fast with an actionable error when the backend exposes fewer
    devices than requested — an engine that silently fell back to fewer
    chips would serve with the wrong per-chip memory budget."""
    import jax

    if devices is None:
        devices = jax.devices()
    if tensor_parallel_size > len(devices):
        raise ValueError(
            f"tensor_parallel_size {tensor_parallel_size} exceeds the "
            f"{len(devices)} device(s) the backend exposes "
            f"({devices[0].platform}); shrink tensor_parallel_size or run "
            "on a larger slice (CPU tests: raise "
            "--xla_force_host_platform_device_count)"
        )
    return MeshSpec(tp=tensor_parallel_size).build(
        devices[:tensor_parallel_size]
    )


@dataclass
class SliceTopology:
    """Description of a TPU slice as scheduled by the placement layer:
    a slice is an atomic multi-host placement group (SURVEY.md §7 phase 2)."""

    num_hosts: int
    chips_per_host: int
    generation: str = "v5e"

    @property
    def num_chips(self) -> int:
        return self.num_hosts * self.chips_per_host

    def bundle_specs(self) -> list[dict[str, float]]:
        """One STRICT_SPREAD bundle per host, each carrying that host's chips."""
        return [
            {"TPU": float(self.chips_per_host), "CPU": 1.0}
            for _ in range(self.num_hosts)
        ]


def initialize_multi_host(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
) -> None:
    """Per-host JAX distributed init (the mesh-forming actor group calls this
    once per host before building the global mesh). Thin wrapper so tests can
    fake it; real multi-host TPU uses jax.distributed.initialize over DCN."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
