"""Pipeline parallelism — GPipe microbatch schedule over the `pp` mesh axis.

The reference has no in-tree pipeline parallelism (SURVEY.md §2.4: delegated
to Alpa release tests only); this is designed fresh the TPU way: all stages
run inside ONE jitted program under `shard_map` over `pp`, activations move
between neighbor stages with `lax.ppermute` (XLA lowers to collective-permute
over ICI/DCN), and the fill/drain schedule is a `lax.scan` — no host-side
per-stage actors on the hot path, so XLA overlaps the permute with compute.

Schedule (GPipe): with S stages and M microbatches, step t ∈ [0, M+S-1);
stage s computes microbatch (t - s) when 0 ≤ t - s < M. Bubble fraction is
(S-1)/(M+S-1) — callers pick M ≥ 4·S to amortize.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map


def stack_stage_params(stage_params: list) -> Any:
    """Stack per-stage param pytrees on a new leading `pp` axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: int,
) -> jnp.ndarray:
    """Run x through S pipelined stages; differentiable end to end.

    stage_fn(params_s, h) -> h' must keep the activation shape (classic
    homogeneous-stage pipelining). `stacked_params` leaves have a leading
    S axis (stack_stage_params) sharded over `pp`; `x` is [batch, ...] with
    batch divisible by num_microbatches.
    """
    n_stages = mesh.shape["pp"]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked_params leading axis {leaf.shape[0]} != pp axis "
                f"{n_stages}; shard_map would silently drop stages"
            )
    M = num_microbatches
    batch = x.shape[0]
    assert batch % M == 0, f"batch {batch} not divisible by microbatches {M}"
    mb = batch // M
    microbatches = x.reshape((M, mb) + x.shape[1:])

    params_spec = jax.tree_util.tree_map(lambda _: P("pp"), stacked_params)
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(params_spec, P()),
        out_specs=P(),
        check_vma=False,
    )
    def run(params, mbs):
        # Each pp rank holds its stage's params with a leading axis of 1.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index("pp")
        act_shape = (mb,) + mbs.shape[2:]

        def step(carry, t):
            recv, acc = carry
            # Stage 0 reads microbatch t (clamped; masked past M).
            feed = jnp.where(
                t < M,
                jax.lax.dynamic_index_in_dim(
                    mbs, jnp.minimum(t, M - 1), axis=0, keepdims=False
                ),
                jnp.zeros(act_shape, mbs.dtype),
            )
            inp = jnp.where(stage == 0, feed, recv)
            active = jnp.logical_and(t - stage >= 0, t - stage < M)
            out = stage_fn(params, inp)
            out = jnp.where(active, out, jnp.zeros_like(out))
            # Last stage banks microbatch (t - (S-1)) into the accumulator.
            out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            write = jnp.logical_and(stage == n_stages - 1, active)
            acc = jax.lax.dynamic_update_index_in_dim(
                acc,
                jnp.where(
                    write,
                    out,
                    jax.lax.dynamic_index_in_dim(acc, out_idx, 0, keepdims=False),
                ),
                out_idx,
                axis=0,
            )
            # Ship activations to the next stage (rank 0 receives zeros).
            recv = (
                jax.lax.ppermute(out, "pp", fwd_perm)
                if n_stages > 1
                else jnp.zeros_like(out)
            )
            return (recv, acc), None

        init = (
            jnp.zeros(act_shape, mbs.dtype),
            jnp.zeros((M,) + act_shape, mbs.dtype),
        )
        (recv, acc), _ = jax.lax.scan(
            step, init, jnp.arange(M + n_stages - 1)
        )
        # Only the last stage holds real outputs; psum broadcasts them so the
        # result is replicated (out_specs P()); other ranks contribute zeros.
        keep = jnp.where(stage == n_stages - 1, 1.0, 0.0).astype(acc.dtype)
        return jax.lax.psum(acc * keep, "pp")

    out = run(stacked_params, microbatches)
    return out.reshape((batch,) + out.shape[2:])
