"""Logical-axis sharding rules.

The GSPMD idiom (scaling-book recipe): name every tensor dimension with a
*logical* axis, map logical axes → mesh axes with one rules table per parallelism
strategy, and let XLA insert the collectives. This single table is the
re-design of everything the reference delegates to torch DDP/FSDP/DeepSpeed
(train/torch/train_loop_utils.py:245,329,339 prepare_model): DP/FSDP/TP/SP all
become different rows in the table, not different wrapper classes.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical axis names used by the model zoo (models/).
#   batch      — per-example batch dim
#   seq        — sequence/token dim (sharded under SP)
#   embed      — model/hidden dim
#   mlp        — feed-forward intermediate dim
#   heads      — attention heads dim
#   kv         — per-head dim
#   vocab      — vocabulary dim
#   expert     — MoE expert dim
#   conv_out / conv_in — conv channel dims

RuleTable = dict[str, Any]  # logical axis -> mesh axis | tuple | None

# Pure data parallel: params replicated, batch split over every data-ish axis.
DP_RULES: RuleTable = {
    "batch": ("dp", "fsdp"),
    "seq": None,
    "embed": None,
    "mlp": None,
    "heads": None,
    "kv": None,
    "vocab": None,
    "expert": None,
    "conv_out": None,
    "conv_in": None,
}

# FSDP/ZeRO-3: params sharded over the fsdp axis on their largest dim.
FSDP_RULES: RuleTable = {
    **DP_RULES,
    "embed": "fsdp",
}

# Megatron TP on top of FSDP: hidden-splitting matmuls over tp.
TP_RULES: RuleTable = {
    "batch": ("dp", "fsdp"),
    "seq": None,
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "vocab": "tp",
    "expert": None,
    "conv_out": "tp",
    "conv_in": None,
}

# Sequence parallel for long context: activations sharded on seq.
SP_RULES: RuleTable = {
    **TP_RULES,
    "seq": "sp",
}

# MoE: experts over ep.
EP_RULES: RuleTable = {
    **TP_RULES,
    "expert": "ep",
}

STRATEGY_RULES: dict[str, RuleTable] = {
    "dp": DP_RULES,
    "fsdp": FSDP_RULES,
    "tp+fsdp": TP_RULES,
    "sp+fsdp": SP_RULES,
    "ep": EP_RULES,
}


def spec_for(logical_axes: Sequence[Optional[str]], rules: RuleTable) -> P:
    """PartitionSpec for a tensor whose dims carry these logical names."""
    entries = []
    for name in logical_axes:
        if name is None:
            entries.append(None)
        else:
            if name not in rules:
                raise KeyError(f"Unknown logical axis {name!r}")
            entries.append(rules[name])
    # Trailing Nones are implicit.
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def named_sharding(
    mesh: Mesh, logical_axes: Sequence[Optional[str]], rules: RuleTable
) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def tree_shardings(mesh: Mesh, logical_tree: Any, rules: RuleTable) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree_util.tree_map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, (tuple, list))
        and all(isinstance(e, (str, type(None))) for e in x),
    )


def infer_param_sharding(
    mesh: Mesh, params: Any, rules: RuleTable, min_shard_size: int = 2**16
) -> Any:
    """Heuristic sharding for an unannotated param tree (FSDP-style): shard the
    largest divisible dim of big params over the fsdp axis, replicate the rest.

    Used when a model has no logical-axis annotations (user-supplied flax
    modules) — the analog of torch FSDP auto-wrapping
    (train/torch/train_loop_utils.py:339).
    """
    fsdp_size = mesh.shape.get("fsdp", 1)

    def shard_one(x):
        if fsdp_size == 1 or x.size < min_shard_size:
            return NamedSharding(mesh, P())
        # Pick the largest dim divisible by the fsdp axis.
        best = None
        for i, d in enumerate(x.shape):
            if d % fsdp_size == 0 and (best is None or d > x.shape[best]):
                best = i
        if best is None:
            return NamedSharding(mesh, P())
        entries: list = [None] * x.ndim
        entries[best] = "fsdp"
        while entries and entries[-1] is None:
            entries.pop()
        return NamedSharding(mesh, P(*entries))

    return jax.tree_util.tree_map(shard_one, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input-batch sharding: split over all data axes (dp, fsdp)."""
    return NamedSharding(mesh, P(("dp", "fsdp")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------- LLM serving: intra-replica tensor parallelism ----------------

# The serving engine's rules table (ray_tpu.llm with
# EngineConfig.tensor_parallel_size > 1): pure Megatron-style TP over the
# `tp` mesh axis, nothing else. Attention heads and the MLP intermediate
# shard (qkv / mlp-in kernels column-parallel, attn-proj / mlp-out kernels
# row-parallel — each block pays exactly one psum after attn-proj and one
# after mlp-out, inserted by GSPMD); embeddings, layernorms, and the tied
# LM head stay replicated so the per-slot argmax needs no gather. The paged
# KV pools shard on the SAME head axis (see llm/model_runner.py), which is
# what makes block ids shard-invariant: every chip holds the same blocks,
# just its own heads' slice of them.
LLM_TP_RULES: RuleTable = {
    **DP_RULES,
    "batch": None,
    "mlp": "tp",
    "heads": "tp",
}

# Head-carrying engine arrays all put H at dim 2 — queries/new K/V
# [B, S, H, D], per-layer cache pools [N, bs, H, D], scale pools
# [N, bs, H] — so one spec covers the whole paged-attention signature.
LLM_HEAD_SPEC = P(None, None, "tp")
# Full cache/scale pools [L, N, bs, H, ...]: H at dim 3.
LLM_POOL_SPEC = P(None, None, None, "tp")


def llm_pool_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the runner's [L, N, bs, H, D] KV pools and
    [L, N, bs, H] int8 scale pools (one spec fits both: H is dim 3)."""
    return NamedSharding(mesh, LLM_POOL_SPEC)


def llm_shard_params(mesh: Mesh, params: Any) -> Any:
    """Place a GPT param tree onto the serving mesh under LLM_TP_RULES
    (boxed metadata is preserved — flax unboxes at apply time).

    Flax-initialized params carry logical axis names in their
    `nn.LogicallyPartitioned` boxes (models/gpt.py annotates every weight)
    — those drive the specs directly. Plain-array trees (a checkpoint
    saved unboxed) fall back to replication: correct, just not
    memory-sharded, and nothing in the step loop depends on where a
    replicated weight lives."""
    from flax.core import meta

    def put(x):
        if isinstance(x, meta.AxisMetadata):
            sharding = named_sharding(mesh, x.names, LLM_TP_RULES)
            return x.replace_boxed(jax.device_put(x.unbox(), sharding))
        return jax.device_put(x, replicated(mesh))

    return jax.tree_util.tree_map(
        put, params, is_leaf=lambda x: isinstance(x, meta.AxisMetadata)
    )
