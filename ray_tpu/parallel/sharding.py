"""Logical-axis sharding rules.

The GSPMD idiom (scaling-book recipe): name every tensor dimension with a
*logical* axis, map logical axes → mesh axes with one rules table per parallelism
strategy, and let XLA insert the collectives. This single table is the
re-design of everything the reference delegates to torch DDP/FSDP/DeepSpeed
(train/torch/train_loop_utils.py:245,329,339 prepare_model): DP/FSDP/TP/SP all
become different rows in the table, not different wrapper classes.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical axis names used by the model zoo (models/).
#   batch      — per-example batch dim
#   seq        — sequence/token dim (sharded under SP)
#   embed      — model/hidden dim
#   mlp        — feed-forward intermediate dim
#   heads      — attention heads dim
#   kv         — per-head dim
#   vocab      — vocabulary dim
#   expert     — MoE expert dim
#   conv_out / conv_in — conv channel dims

RuleTable = dict[str, Any]  # logical axis -> mesh axis | tuple | None

# Pure data parallel: params replicated, batch split over every data-ish axis.
DP_RULES: RuleTable = {
    "batch": ("dp", "fsdp"),
    "seq": None,
    "embed": None,
    "mlp": None,
    "heads": None,
    "kv": None,
    "vocab": None,
    "expert": None,
    "conv_out": None,
    "conv_in": None,
}

# FSDP/ZeRO-3: params sharded over the fsdp axis on their largest dim.
FSDP_RULES: RuleTable = {
    **DP_RULES,
    "embed": "fsdp",
}

# Megatron TP on top of FSDP: hidden-splitting matmuls over tp.
TP_RULES: RuleTable = {
    "batch": ("dp", "fsdp"),
    "seq": None,
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "vocab": "tp",
    "expert": None,
    "conv_out": "tp",
    "conv_in": None,
}

# Sequence parallel for long context: activations sharded on seq.
SP_RULES: RuleTable = {
    **TP_RULES,
    "seq": "sp",
}

# MoE: experts over ep.
EP_RULES: RuleTable = {
    **TP_RULES,
    "expert": "ep",
}

STRATEGY_RULES: dict[str, RuleTable] = {
    "dp": DP_RULES,
    "fsdp": FSDP_RULES,
    "tp+fsdp": TP_RULES,
    "sp+fsdp": SP_RULES,
    "ep": EP_RULES,
}


def spec_for(logical_axes: Sequence[Optional[str]], rules: RuleTable) -> P:
    """PartitionSpec for a tensor whose dims carry these logical names."""
    entries = []
    for name in logical_axes:
        if name is None:
            entries.append(None)
        else:
            if name not in rules:
                raise KeyError(f"Unknown logical axis {name!r}")
            entries.append(rules[name])
    # Trailing Nones are implicit.
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def named_sharding(
    mesh: Mesh, logical_axes: Sequence[Optional[str]], rules: RuleTable
) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def tree_shardings(mesh: Mesh, logical_tree: Any, rules: RuleTable) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree_util.tree_map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, (tuple, list))
        and all(isinstance(e, (str, type(None))) for e in x),
    )


def infer_param_sharding(
    mesh: Mesh, params: Any, rules: RuleTable, min_shard_size: int = 2**16
) -> Any:
    """Heuristic sharding for an unannotated param tree (FSDP-style): shard the
    largest divisible dim of big params over the fsdp axis, replicate the rest.

    Used when a model has no logical-axis annotations (user-supplied flax
    modules) — the analog of torch FSDP auto-wrapping
    (train/torch/train_loop_utils.py:339).
    """
    fsdp_size = mesh.shape.get("fsdp", 1)

    def shard_one(x):
        if fsdp_size == 1 or x.size < min_shard_size:
            return NamedSharding(mesh, P())
        # Pick the largest dim divisible by the fsdp axis.
        best = None
        for i, d in enumerate(x.shape):
            if d % fsdp_size == 0 and (best is None or d > x.shape[best]):
                best = i
        if best is None:
            return NamedSharding(mesh, P())
        entries: list = [None] * x.ndim
        entries[best] = "fsdp"
        while entries and entries[-1] is None:
            entries.pop()
        return NamedSharding(mesh, P(*entries))

    return jax.tree_util.tree_map(shard_one, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input-batch sharding: split over all data axes (dp, fsdp)."""
    return NamedSharding(mesh, P(("dp", "fsdp")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
