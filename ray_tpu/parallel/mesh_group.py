"""Multi-host mesh formation: one real OS process per TPU host.

The bridge from dynamic task scheduling to static SPMD (SURVEY.md §7 hard
part 2, §2.5): XLA wants every host of a slice to run the same program with a
coordinated `jax.distributed.initialize`; the reference reaches multi-host
through torch.distributed process groups formed inside Train worker actors
(train/torch/config.py:69 _setup_torch_process_group). Here the analog is a
group of PROCESS-ISOLATED actors — each owns a fresh interpreter, sets its
XLA platform/flags before first jax import, joins the distributed runtime,
and then executes arbitrary SPMD functions against the GLOBAL mesh.

On test hardware (no pod), `jax_platform="cpu"` with
`local_device_count=K` forms a genuine multi-process K*num_hosts-device mesh
with gloo-backed cross-process collectives — the same code path a v5e pod
takes over ICI/DCN with `jax_platform=None` on real hosts.
"""

from __future__ import annotations

import re
import socket
from typing import Any, Callable, Optional, Sequence


class MeshHostWorker:
    """Actor hosted in its own process: one per TPU host of the slice."""

    def __init__(
        self,
        process_id: int,
        num_processes: int,
        coordinator_address: str,
        local_device_count: Optional[int] = None,
        jax_platform: Optional[str] = "cpu",
    ):
        import os

        # Platform/flags MUST land before the first jax import in this
        # process (the whole reason these workers are process-isolated).
        if jax_platform:
            os.environ["JAX_PLATFORMS"] = jax_platform
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        if local_device_count:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+",
                "",
                os.environ.get("XLA_FLAGS", ""),
            )
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={local_device_count}"
            ).strip()
        import jax

        if jax_platform:
            jax.config.update("jax_platforms", jax_platform)
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        self.process_id = process_id

    def device_counts(self) -> tuple[int, int]:
        import jax

        return jax.device_count(), jax.local_device_count()

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        """Execute fn in this host process (fn sees the global mesh via
        jax.devices(); every host must run the same SPMD program)."""
        return fn(*args, **kwargs)

    def build_mesh_and_run(
        self, axis_shape: Sequence[int], axis_names: Sequence[str], fn: Callable,
        *args, **kwargs
    ) -> Any:
        """Convenience: build a Mesh over the GLOBAL device list and pass it
        to fn as the first argument."""
        import numpy as np
        import jax
        from jax.sharding import Mesh

        devices = np.array(jax.devices()).reshape(tuple(axis_shape))
        mesh = Mesh(devices, tuple(axis_names))
        return fn(mesh, *args, **kwargs)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class MeshWorkerGroup:
    """N process-isolated actors forming one jax.distributed world.

    Usage::

        group = MeshWorkerGroup(num_hosts=2, local_device_count=4)
        group.start()                      # blocks until the world is formed
        results = group.run(spmd_fn, x)    # one result per host
        group.shutdown()
    """

    def __init__(
        self,
        num_hosts: int,
        local_device_count: Optional[int] = None,
        jax_platform: Optional[str] = "cpu",
        coordinator_address: Optional[str] = None,
        placement_group=None,
    ):
        self.num_hosts = num_hosts
        self.local_device_count = local_device_count
        self.jax_platform = jax_platform
        self.coordinator_address = coordinator_address or f"127.0.0.1:{_free_port()}"
        self._placement_group = placement_group
        self.workers: list = []

    def start(self, timeout: float = 120.0) -> "MeshWorkerGroup":
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        actor_cls = ray_tpu.remote(MeshHostWorker)
        options: dict = {"isolation": "process", "num_cpus": 0}
        for i in range(self.num_hosts):
            if self._placement_group is not None:
                options["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=self._placement_group,
                    placement_group_bundle_index=i,
                )
            self.workers.append(
                actor_cls.options(**options).remote(
                    process_id=i,
                    num_processes=self.num_hosts,
                    coordinator_address=self.coordinator_address,
                    local_device_count=self.local_device_count,
                    jax_platform=self.jax_platform,
                )
            )
        # Barrier: every host reports the same global device count.
        counts = ray_tpu.get(
            [w.device_counts.remote() for w in self.workers], timeout=timeout
        )
        globals_ = {c[0] for c in counts}
        if len(globals_) != 1:
            raise RuntimeError(f"inconsistent global device counts: {counts}")
        self.global_device_count = counts[0][0]
        self.local_device_counts = [c[1] for c in counts]
        return self

    def run(self, fn: Callable, *args, timeout: Optional[float] = None, **kwargs):
        """Run the same SPMD fn on every host; returns one result per host."""
        import ray_tpu

        return ray_tpu.get(
            [w.run.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=timeout,
        )

    def run_with_mesh(
        self,
        axis_shape: Sequence[int],
        axis_names: Sequence[str],
        fn: Callable,
        *args,
        timeout: Optional[float] = None,
        **kwargs,
    ):
        import ray_tpu

        return ray_tpu.get(
            [
                w.build_mesh_and_run.remote(
                    tuple(axis_shape), tuple(axis_names), fn, *args, **kwargs
                )
                for w in self.workers
            ],
            timeout=timeout,
        )

    def shutdown(self) -> None:
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
