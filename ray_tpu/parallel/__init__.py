from ray_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshSpec,
    SliceTopology,
    auto_mesh,
    tensor_parallel_mesh,
)
from ray_tpu.parallel.mesh_group import MeshHostWorker, MeshWorkerGroup
from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from ray_tpu.parallel.sharding import (
    DP_RULES,
    EP_RULES,
    FSDP_RULES,
    LLM_TP_RULES,
    SP_RULES,
    STRATEGY_RULES,
    TP_RULES,
    batch_sharding,
    infer_param_sharding,
    named_sharding,
    replicated,
    spec_for,
    tree_shardings,
)

__all__ = [
    "AXIS_ORDER",
    "DP_RULES",
    "EP_RULES",
    "FSDP_RULES",
    "LLM_TP_RULES",
    "MeshHostWorker",
    "MeshSpec",
    "MeshWorkerGroup",
    "SP_RULES",
    "STRATEGY_RULES",
    "SliceTopology",
    "TP_RULES",
    "auto_mesh",
    "batch_sharding",
    "infer_param_sharding",
    "named_sharding",
    "pipeline_apply",
    "replicated",
    "spec_for",
    "stack_stage_params",
    "tensor_parallel_mesh",
    "tree_shardings",
]
