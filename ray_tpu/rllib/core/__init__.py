from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian, get_dist_cls
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import (
    MultiAgentRLModule,
    PiVfNet,
    QNet,
    RLModule,
    RLModuleSpec,
)

__all__ = [
    "Categorical",
    "DiagGaussian",
    "Learner",
    "LearnerGroup",
    "MultiAgentRLModule",
    "PiVfNet",
    "QNet",
    "RLModule",
    "RLModuleSpec",
    "get_dist_cls",
]
