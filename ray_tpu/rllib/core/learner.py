"""Learner — jitted SGD over an RLModule, optionally sharded over a mesh.

Reference: rllib/core/learner/learner.py (:170 build, :482 update, :604
compute_gradients, :1086 apply_gradients) and torch_learner.py:51 (framework
learner). The TPU re-design: instead of a DDP-wrapped torch module, the whole
(loss → grad → optimizer) step is ONE jitted function; data parallelism is a
`dp` mesh axis with the batch sharded and params replicated, so XLA emits the
gradient all-reduce over ICI (no NCCL, no wrapper class — SURVEY.md §2.5).
Subclasses implement `compute_loss(params, batch, rng)` returning
(scalar_loss, metrics_dict); everything else is generic.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec
from ray_tpu.rllib.policy.sample_batch import SampleBatch

DEVICE_COLUMNS_EXCLUDED = (SampleBatch.INFOS,)


def _to_device_batch(batch: Mapping) -> dict:
    return {
        k: np.asarray(v)
        for k, v in batch.items()
        if k not in DEVICE_COLUMNS_EXCLUDED and isinstance(v, (np.ndarray, jnp.ndarray))
    }


class Learner:
    """Owns module params + optax state; runs the jitted update."""

    # Subclasses whose loss depends on intra-batch row order (V-trace
    # fragments) set this False; minibatches then iterate in input order.
    shuffle_minibatches = True

    def __init__(
        self,
        module_spec: RLModuleSpec,
        config: Optional[Any] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
    ):
        self.config = config
        self.module_spec = module_spec
        self.module: Optional[RLModule] = None
        self.mesh = mesh
        self._opt_state = None
        self._update_fn: Optional[Callable] = None
        self._grad_fn: Optional[Callable] = None
        self._rng = jax.random.PRNGKey(getattr(config, "seed", 0) or 0)
        self._built = False

    # -- construction -----------------------------------------------------

    def build(self) -> None:
        if self._built:
            return
        self.module = self.module_spec.build()
        self.optimizer = self.configure_optimizer()
        self._opt_state = self.optimizer.init(self.module.params)
        # Read-only pytree fed into the jitted loss as a traced input
        # (target networks etc.) — mutated host-side in after_update without
        # forcing a re-trace.
        self.extra_train_state = self.initial_extra_state()
        self._built = True

    def initial_extra_state(self) -> Any:
        return {}

    def configure_optimizer(self) -> optax.GradientTransformation:
        lr = getattr(self.config, "lr", 5e-4) if self.config else 5e-4
        clip = getattr(self.config, "grad_clip", None) if self.config else None
        chain = []
        if clip:
            chain.append(optax.clip_by_global_norm(clip))
        chain.append(optax.adam(lr))
        return optax.chain(*chain)

    # -- algorithm hook ----------------------------------------------------

    def compute_loss(
        self, params, batch: Mapping, rng, extra=None
    ) -> Tuple[jnp.ndarray, dict]:
        raise NotImplementedError

    # -- update path -------------------------------------------------------

    def _make_update_fn(self):
        optimizer = self.optimizer

        def update_step(params, opt_state, extra, batch, rng):
            (loss, metrics), grads = jax.value_and_grad(
                self.compute_loss, has_aux=True
            )(params, batch, rng, extra)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, metrics

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self.mesh
            data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
            replicated = NamedSharding(mesh, P())
            batch_sharding = NamedSharding(mesh, P(data_axes))
            jitted = jax.jit(
                update_step,
                in_shardings=(
                    replicated,
                    replicated,
                    replicated,
                    batch_sharding,
                    replicated,
                ),
                out_shardings=(replicated, replicated, replicated),
                donate_argnums=(0, 1),
            )
        else:
            jitted = jax.jit(update_step, donate_argnums=(0, 1))
        return jitted

    def update(self, batch: SampleBatch) -> dict:
        """One pass of minibatch SGD over `batch`; returns averaged metrics
        (reference learner.py:482 update semantics).

        The whole epochs x minibatches loop runs INSIDE one jitted call
        (permutations, dynamic-slice minibatching and the SGD chain as a
        lax.scan): the host uploads the batch once and syncs once. Through a
        remote TPU this is the difference between 1 and epochs*minibatches
        round trips per update (~500ms each on a tunneled chip)."""
        assert self._built, "call build() first"
        cfg = self.config
        minibatch_size = getattr(cfg, "minibatch_size", None) or batch.count
        num_epochs = getattr(cfg, "num_epochs", 1) or 1
        if self.mesh is None:
            out = self._update_scanned(batch, int(minibatch_size), int(num_epochs))
            self.after_update(batch)
            return out
        # Mesh path: per-minibatch jitted steps (the sharded permutation
        # gather is a cross-device shuffle; keep the simple loop here).
        if self._update_fn is None:
            self._update_fn = self._make_update_fn()
        all_metrics = []
        for mb in batch.minibatches(
            minibatch_size, num_epochs=num_epochs, shuffle=self.shuffle_minibatches
        ):
            self._rng, key = jax.random.split(self._rng)
            device_batch = _to_device_batch(mb)
            self.module.params, self._opt_state, metrics = self._update_fn(
                self.module.params,
                self._opt_state,
                self.extra_train_state,
                device_batch,
                key,
            )
            all_metrics.append(metrics)
        out = {
            k: float(np.mean([jax.device_get(m[k]) for m in all_metrics]))
            for k in all_metrics[0]
        }
        self.after_update(batch)
        return out

    def _make_scanned_update_fn(self, n: int, num_minibatches: int,
                                minibatch_size: int, num_epochs: int):
        optimizer = self.optimizer
        shuffle = self.shuffle_minibatches
        n_rows = num_minibatches * minibatch_size

        def full_update(params, opt_state, extra, batch, rng):
            def epoch_body(carry, epoch_key):
                params, opt_state = carry
                # Permute over ALL n rows, then take the first n_rows of the
                # permutation: DIFFERENT remainder rows drop each epoch, so
                # every collected row participates (matching the old
                # shuffle-then-slice minibatch loop).
                perm = (
                    jax.random.permutation(epoch_key, n)[:n_rows]
                    if shuffle
                    else jnp.arange(n_rows)
                )

                def mb_body(carry2, mb_idx):
                    params, opt_state = carry2
                    take = jax.lax.dynamic_slice_in_dim(
                        perm, mb_idx * minibatch_size, minibatch_size
                    )
                    mb = {k: jnp.take(v, take, axis=0) for k, v in batch.items()}
                    mb_key = jax.random.fold_in(epoch_key, mb_idx)
                    (loss, metrics), grads = jax.value_and_grad(
                        self.compute_loss, has_aux=True
                    )(params, mb, mb_key, extra)
                    updates, opt_state = optimizer.update(grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    metrics = dict(metrics)
                    metrics["total_loss"] = loss
                    metrics["grad_norm"] = optax.global_norm(grads)
                    return (params, opt_state), metrics

                (params, opt_state), mb_metrics = jax.lax.scan(
                    mb_body, (params, opt_state), jnp.arange(num_minibatches)
                )
                return (params, opt_state), mb_metrics

            epoch_keys = jax.random.split(rng, num_epochs)
            (params, opt_state), metrics = jax.lax.scan(
                epoch_body, (params, opt_state), epoch_keys
            )
            mean_metrics = jax.tree_util.tree_map(jnp.mean, metrics)
            return params, opt_state, mean_metrics

        return jax.jit(full_update, donate_argnums=(0, 1))

    def _update_scanned(self, batch: SampleBatch, minibatch_size: int,
                        num_epochs: int) -> dict:
        device_batch = _to_device_batch(batch)
        n = batch.count
        minibatch_size = min(minibatch_size, n)
        num_minibatches = max(1, n // minibatch_size)
        n_rows = num_minibatches * minibatch_size
        if n_rows != n and not self.shuffle_minibatches:
            # Order-dependent losses (V-trace fragments) can't resample the
            # remainder; drop the partial tail like the old minibatch loop.
            device_batch = {k: v[:n_rows] for k, v in device_batch.items()}
            n = n_rows
        cache_key = (n, num_minibatches, minibatch_size, num_epochs)
        if not hasattr(self, "_scanned_fns"):
            self._scanned_fns = {}
        fn = self._scanned_fns.get(cache_key)
        if fn is None:
            fn = self._make_scanned_update_fn(
                n, num_minibatches, minibatch_size, num_epochs
            )
            self._scanned_fns[cache_key] = fn
        self._rng, key = jax.random.split(self._rng)
        self.module.params, self._opt_state, metrics = fn(
            self.module.params,
            self._opt_state,
            self.extra_train_state,
            device_batch,
            key,
        )
        return {k: float(v) for k, v in jax.device_get(metrics).items()}

    def after_update(self, batch: SampleBatch) -> None:
        """Post-update hook (target-network sync etc.)."""

    # -- gradient-level API (reference learner.py:604,:1086) ---------------

    def compute_gradients(self, batch: SampleBatch) -> Tuple[Any, dict]:
        assert self._built
        if self._grad_fn is None:
            self._grad_fn = jax.jit(
                lambda params, extra, b, rng: jax.value_and_grad(
                    self.compute_loss, has_aux=True
                )(params, b, rng, extra)
            )
        self._rng, key = jax.random.split(self._rng)
        (loss, metrics), grads = self._grad_fn(
            self.module.params, self.extra_train_state, _to_device_batch(batch), key
        )
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        out = {}
        for k, v in metrics.items():
            v = jax.device_get(v)
            # Scalars stay floats; per-sample diagnostics (td errors) pass
            # through as arrays for the LearnerGroup to concatenate.
            out[k] = float(v) if np.ndim(v) == 0 else np.asarray(v)
        return grads, out

    def apply_gradients(self, grads: Any) -> None:
        assert self._built
        updates, self._opt_state = self.optimizer.update(
            grads, self._opt_state, self.module.params
        )
        self.module.params = optax.apply_updates(self.module.params, updates)

    # -- state -------------------------------------------------------------

    def get_weights(self) -> Any:
        return self.module.get_state()

    def set_weights(self, weights: Any) -> None:
        self.module.set_state(weights)

    def get_state(self) -> dict:
        return {
            "weights": jax.device_get(self.module.params),
            "opt_state": jax.device_get(self._opt_state),
            "extra": jax.device_get(self.extra_train_state),
        }

    def set_state(self, state: Mapping) -> None:
        self.module.params = state["weights"]
        self._opt_state = state["opt_state"]
        self.extra_train_state = state.get("extra", self.extra_train_state)


class MultiAgentLearner:
    """Independent per-policy optimization (reference: marl_module.py +
    the per-module update loop in learner.py): one sub-learner per policy,
    each with its OWN parameters and optimizer state. An update routes each
    policy's sub-batch of a MultiAgentBatch to its learner; policies absent
    from a batch are untouched."""

    def __init__(self, learner_builders: Mapping[str, Callable]):
        self._learners = {pid: b() for pid, b in learner_builders.items()}

    def build(self) -> None:
        for learner in self._learners.values():
            learner.build()

    def __getitem__(self, policy_id: str) -> Learner:
        return self._learners[policy_id]

    def keys(self):
        return self._learners.keys()

    def update(self, batch) -> dict:
        out: dict = {}
        for pid, sub in batch.items():
            learner = self._learners.get(pid)
            if learner is None or sub.count == 0:
                continue
            for k, v in learner.update(sub).items():
                out[f"{pid}/{k}"] = v
        return out

    def after_update(self, batch) -> None:
        pass

    def get_weights(self) -> dict:
        return {pid: lr.get_weights() for pid, lr in self._learners.items()}

    def set_weights(self, weights: Mapping) -> None:
        for pid, w in weights.items():
            if pid in self._learners:
                self._learners[pid].set_weights(w)

    def get_state(self) -> dict:
        return {pid: lr.get_state() for pid, lr in self._learners.items()}

    def set_state(self, state: Mapping) -> None:
        for pid, s in state.items():
            if pid in self._learners:
                self._learners[pid].set_state(s)
