"""Action distributions in jax — categorical and diagonal gaussian.

Reference: rllib/models/distributions.py + torch_distributions.py (new-stack
Distribution API: from_logits / sample / logp / entropy / kl). Everything is
pure-functional over jnp arrays so it traces inside the jitted loss and the
jitted action-sampling step alike.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Categorical:
    def __init__(self, logits: jnp.ndarray):
        self.logits = logits - jax.scipy.special.logsumexp(
            logits, axis=-1, keepdims=True
        )

    def sample(self, rng: jax.Array) -> jnp.ndarray:
        return jax.random.categorical(rng, self.logits, axis=-1)

    def deterministic_sample(self) -> jnp.ndarray:
        return jnp.argmax(self.logits, axis=-1)

    def logp(self, actions: jnp.ndarray) -> jnp.ndarray:
        return jnp.take_along_axis(
            self.logits, actions[..., None].astype(jnp.int32), axis=-1
        )[..., 0]

    def entropy(self) -> jnp.ndarray:
        probs = jnp.exp(self.logits)
        return -jnp.sum(probs * self.logits, axis=-1)

    def kl(self, other: "Categorical") -> jnp.ndarray:
        probs = jnp.exp(self.logits)
        return jnp.sum(probs * (self.logits - other.logits), axis=-1)


class DiagGaussian:
    """dist_inputs = concat([mean, log_std], axis=-1)."""

    def __init__(self, dist_inputs: jnp.ndarray):
        self.mean, self.log_std = jnp.split(dist_inputs, 2, axis=-1)
        self.std = jnp.exp(jnp.clip(self.log_std, -20.0, 2.0))

    def sample(self, rng: jax.Array) -> jnp.ndarray:
        return self.mean + self.std * jax.random.normal(rng, self.mean.shape)

    def deterministic_sample(self) -> jnp.ndarray:
        return self.mean

    def logp(self, actions: jnp.ndarray) -> jnp.ndarray:
        z = (actions - self.mean) / self.std
        return jnp.sum(
            -0.5 * z**2 - jnp.log(self.std) - 0.5 * jnp.log(2.0 * jnp.pi), axis=-1
        )

    def entropy(self) -> jnp.ndarray:
        return jnp.sum(
            jnp.log(self.std) + 0.5 * (1.0 + jnp.log(2.0 * jnp.pi)), axis=-1
        )

    def kl(self, other: "DiagGaussian") -> jnp.ndarray:
        return jnp.sum(
            other.log_std
            - self.log_std
            + (self.std**2 + (self.mean - other.mean) ** 2) / (2.0 * other.std**2)
            - 0.5,
            axis=-1,
        )


def get_dist_cls(action_space):
    from ray_tpu.rllib.env.spaces import Box, Discrete

    if isinstance(action_space, Discrete):
        return Categorical
    if isinstance(action_space, Box):
        return DiagGaussian
    raise ValueError(f"No distribution for action space {action_space!r}")


def dist_input_dim(action_space) -> int:
    """Width of the model's action-head output for this space."""
    from ray_tpu.rllib.env.spaces import Box, Discrete
    import numpy as np

    if isinstance(action_space, Discrete):
        return action_space.n
    if isinstance(action_space, Box):
        return 2 * int(np.prod(action_space.shape))
    raise ValueError(f"No distribution for action space {action_space!r}")
