"""RLModule — the neural-network holder of the new stack, in flax.

Reference: rllib/core/rl_module/rl_module.py (RLModule, SingleAgentRLModuleSpec)
and marl_module.py (MultiAgentRLModule). An RLModule owns a flax module + its
params and exposes the three forward passes: `forward_inference` (deterministic
serving), `forward_exploration` (sampling rollouts), `forward_train` (loss
inputs). All three are pure functions of (params, batch) so the Learner can
jit/pjit them; the module object itself holds no device state beyond params.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.distributions import dist_input_dim, get_dist_cls
from ray_tpu.rllib.env.spaces import Space, flat_dim
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class PiVfNet(nn.Module):
    """Default model: shared or separate MLP encoders + pi / vf heads
    (reference: core/models/catalog.py:28 default MLP encoder + heads)."""

    action_dim: int
    hiddens: tuple = (256, 256)
    activation: str = "tanh"
    vf_share_layers: bool = False
    dtype: Any = jnp.float32

    def _encoder(self, x, name):
        act = dict(tanh=nn.tanh, relu=nn.relu, swish=nn.swish)[self.activation]
        for i, width in enumerate(self.hiddens):
            x = nn.Dense(width, dtype=self.dtype, name=f"{name}_{i}")(x)
            x = act(x)
        return x

    @nn.compact
    def __call__(self, obs):
        obs = obs.reshape(obs.shape[0], -1)
        z_pi = self._encoder(obs, "pi")
        z_vf = z_pi if self.vf_share_layers else self._encoder(obs, "vf")
        # Small-init final layers stabilize early PPO updates.
        pi_out = nn.Dense(
            self.action_dim, dtype=self.dtype, name="pi_head",
            kernel_init=nn.initializers.variance_scaling(0.01, "fan_in", "truncated_normal"),
        )(z_pi)
        vf_out = nn.Dense(1, dtype=self.dtype, name="vf_head")(z_vf)
        return pi_out, vf_out[..., 0]


class QNet(nn.Module):
    """Q(s, ·) head for value-based algorithms (DQN)."""

    num_actions: int
    hiddens: tuple = (256, 256)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        x = obs.reshape(obs.shape[0], -1)
        for i, width in enumerate(self.hiddens):
            x = nn.relu(nn.Dense(width, dtype=self.dtype, name=f"q_{i}")(x))
        return nn.Dense(self.num_actions, dtype=self.dtype, name="q_head")(x)


class RLModule:
    """Holds a flax net + params; forward passes are pure functions."""

    def __init__(
        self,
        observation_space: Space,
        action_space: Space,
        model_config: Optional[dict] = None,
        net: Optional[nn.Module] = None,
        seed: int = 0,
    ):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = dict(model_config or {})
        self.dist_cls = get_dist_cls(action_space)
        if net is None:
            net = PiVfNet(
                action_dim=dist_input_dim(action_space),
                hiddens=tuple(self.model_config.get("fcnet_hiddens", (256, 256))),
                activation=self.model_config.get("fcnet_activation", "tanh"),
                vf_share_layers=bool(self.model_config.get("vf_share_layers", False)),
            )
        self.net = net
        dummy = jnp.zeros((1,) + tuple(observation_space.shape), jnp.float32)
        self.params = net.init(jax.random.PRNGKey(seed), dummy)

    # The default module is actor-critic shaped; value-free modules (DQN)
    # set this False so runners skip bootstrap-value computation.
    has_value_head = True

    def exploration_inputs(self, timestep: int) -> Mapping:
        """Extra host-computed arrays merged into the exploration forward's
        batch (epsilon schedules etc.) — traced inputs, never retraces."""
        return {}

    # -- pure forward passes (static over self.net) ----------------------

    def apply(self, params, obs):
        return self.net.apply(params, obs)

    def forward_train(self, params, batch: Mapping) -> dict:
        pi_out, vf = self.apply(params, batch[SampleBatch.OBS])
        return {SampleBatch.ACTION_DIST_INPUTS: pi_out, SampleBatch.VF_PREDS: vf}

    def forward_exploration(self, params, batch: Mapping, rng) -> dict:
        pi_out, vf = self.apply(params, batch[SampleBatch.OBS])
        dist = self.dist_cls(pi_out)
        actions = dist.sample(rng)
        return {
            SampleBatch.ACTIONS: actions,
            SampleBatch.ACTION_LOGP: dist.logp(actions),
            SampleBatch.ACTION_DIST_INPUTS: pi_out,
            SampleBatch.VF_PREDS: vf,
        }

    def forward_inference(self, params, batch: Mapping) -> dict:
        pi_out, _ = self.apply(params, batch[SampleBatch.OBS])
        return {SampleBatch.ACTIONS: self.dist_cls(pi_out).deterministic_sample()}

    # -- state ------------------------------------------------------------

    def get_state(self) -> Any:
        return jax.device_get(self.params)

    def set_state(self, params: Any) -> None:
        self.params = params


@dataclasses.dataclass
class RLModuleSpec:
    """Serializable recipe for building an RLModule on a remote worker
    (reference: SingleAgentRLModuleSpec, rl_module.py)."""

    module_class: type = RLModule
    observation_space: Optional[Space] = None
    action_space: Optional[Space] = None
    model_config: Optional[dict] = None
    net_builder: Optional[Callable[[], nn.Module]] = None
    seed: int = 0

    def build(self) -> RLModule:
        net = self.net_builder() if self.net_builder else None
        return self.module_class(
            self.observation_space,
            self.action_space,
            model_config=self.model_config,
            net=net,
            seed=self.seed,
        )


class MultiAgentRLModule:
    """{module_id: RLModule} container (reference: marl_module.py)."""

    def __init__(self, modules: Mapping[str, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def get_state(self) -> dict:
        return {mid: m.get_state() for mid, m in self._modules.items()}

    def set_state(self, state: Mapping) -> None:
        for mid, s in state.items():
            self._modules[mid].set_state(s)
