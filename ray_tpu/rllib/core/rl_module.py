"""RLModule — the neural-network holder of the new stack, in flax.

Reference: rllib/core/rl_module/rl_module.py (RLModule, SingleAgentRLModuleSpec)
and marl_module.py (MultiAgentRLModule). An RLModule owns a flax module + its
params and exposes the three forward passes: `forward_inference` (deterministic
serving), `forward_exploration` (sampling rollouts), `forward_train` (loss
inputs). All three are pure functions of (params, batch) so the Learner can
jit/pjit them; the module object itself holds no device state beyond params.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.distributions import dist_input_dim, get_dist_cls
from ray_tpu.rllib.env.spaces import Space, flat_dim
from ray_tpu.rllib.policy.sample_batch import SampleBatch


def _np_logsumexp(x: np.ndarray) -> np.ndarray:
    m = np.max(x, axis=-1, keepdims=True)
    return m + np.log(np.sum(np.exp(x - m), axis=-1, keepdims=True))


class PiVfNet(nn.Module):
    """Default model: shared or separate MLP encoders + pi / vf heads
    (reference: core/models/catalog.py:28 default MLP encoder + heads)."""

    action_dim: int
    hiddens: tuple = (256, 256)
    activation: str = "tanh"
    vf_share_layers: bool = False
    dtype: Any = jnp.float32

    def _encoder(self, x, name):
        act = dict(tanh=nn.tanh, relu=nn.relu, swish=nn.swish)[self.activation]
        for i, width in enumerate(self.hiddens):
            x = nn.Dense(width, dtype=self.dtype, name=f"{name}_{i}")(x)
            x = act(x)
        return x

    @nn.compact
    def __call__(self, obs):
        obs = obs.reshape(obs.shape[0], -1)
        z_pi = self._encoder(obs, "pi")
        z_vf = z_pi if self.vf_share_layers else self._encoder(obs, "vf")
        # Small-init final layers stabilize early PPO updates.
        pi_out = nn.Dense(
            self.action_dim, dtype=self.dtype, name="pi_head",
            kernel_init=nn.initializers.variance_scaling(0.01, "fan_in", "truncated_normal"),
        )(z_pi)
        vf_out = nn.Dense(1, dtype=self.dtype, name="vf_head")(z_vf)
        return pi_out, vf_out[..., 0]


class QNet(nn.Module):
    """Q(s, ·) head for value-based algorithms (DQN).

    dueling=True splits the torso into V(s) + A(s, ·) streams recombined as
    Q = V + A - mean(A) (Wang et al. 2016; reference:
    rllib dqn catalog's dueling head)."""

    num_actions: int
    hiddens: tuple = (256, 256)
    dtype: Any = jnp.float32
    dueling: bool = False

    @nn.compact
    def __call__(self, obs):
        x = obs.reshape(obs.shape[0], -1)
        for i, width in enumerate(self.hiddens):
            x = nn.relu(nn.Dense(width, dtype=self.dtype, name=f"q_{i}")(x))
        if not self.dueling:
            return nn.Dense(
                self.num_actions, dtype=self.dtype, name="q_head"
            )(x)
        value = nn.Dense(1, dtype=self.dtype, name="value_head")(x)
        adv = nn.Dense(
            self.num_actions, dtype=self.dtype, name="advantage_head"
        )(x)
        return value + adv - jnp.mean(adv, axis=-1, keepdims=True)


class RLModule:
    """Holds a flax net + params; forward passes are pure functions."""

    def __init__(
        self,
        observation_space: Space,
        action_space: Space,
        model_config: Optional[dict] = None,
        net: Optional[nn.Module] = None,
        seed: int = 0,
    ):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = dict(model_config or {})
        self.dist_cls = get_dist_cls(action_space)
        if net is None:
            net = PiVfNet(
                action_dim=dist_input_dim(action_space),
                hiddens=tuple(self.model_config.get("fcnet_hiddens", (256, 256))),
                activation=self.model_config.get("fcnet_activation", "tanh"),
                vf_share_layers=bool(self.model_config.get("vf_share_layers", False)),
            )
        self.net = net
        dummy = jnp.zeros((1,) + tuple(observation_space.shape), jnp.float32)
        self.params = net.init(jax.random.PRNGKey(seed), dummy)

    # The default module is actor-critic shaped; value-free modules (DQN)
    # set this False so runners skip bootstrap-value computation.
    has_value_head = True

    def exploration_inputs(self, timestep: int) -> Mapping:
        """Extra host-computed arrays merged into the exploration forward's
        batch (epsilon schedules etc.) — traced inputs, never retraces."""
        return {}

    # -- pure forward passes (static over self.net) ----------------------

    def apply(self, params, obs):
        return self.net.apply(params, obs)

    def forward_train(self, params, batch: Mapping) -> dict:
        pi_out, vf = self.apply(params, batch[SampleBatch.OBS])
        return {SampleBatch.ACTION_DIST_INPUTS: pi_out, SampleBatch.VF_PREDS: vf}

    def forward_exploration(self, params, batch: Mapping, rng) -> dict:
        pi_out, vf = self.apply(params, batch[SampleBatch.OBS])
        dist = self.dist_cls(pi_out)
        actions = dist.sample(rng)
        return {
            SampleBatch.ACTIONS: actions,
            SampleBatch.ACTION_LOGP: dist.logp(actions),
            SampleBatch.ACTION_DIST_INPUTS: pi_out,
            SampleBatch.VF_PREDS: vf,
        }

    def forward_inference(self, params, batch: Mapping) -> dict:
        pi_out, _ = self.apply(params, batch[SampleBatch.OBS])
        return {SampleBatch.ACTIONS: self.dist_cls(pi_out).deterministic_sample()}

    # -- numpy rollout fast path ------------------------------------------

    def np_exploration_fn(self) -> Optional[Callable]:
        """A pure-numpy forward_exploration for CPU rollout hosts, or None.

        A jitted call costs ~350us of dispatch per env step on CPU — 10x
        the actual math for the default MLP — and dominated sampling
        throughput (the reference's analog is running the torch policy
        on the rollout worker's CPU). Only the stock PiVfNet +
        Categorical/DiagGaussian combination qualifies; custom nets and
        overridden forward_exploration keep the jitted path. Weights are
        re-extracted to numpy lazily after each set_state.

        Returns fn(obs, np_rng) -> fwd dict (same keys/semantics as
        forward_exploration)."""
        from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian

        if type(self).forward_exploration is not RLModule.forward_exploration:
            return None
        if not isinstance(self.net, PiVfNet):
            return None
        if self.dist_cls not in (Categorical, DiagGaussian):
            return None
        return self._np_explore

    def _np_weights(self):
        cached = getattr(self, "_np_weight_cache", None)
        if cached is not None and cached[0] is self.params:
            return cached[1]
        p = jax.device_get(self.params)["params"]
        net: PiVfNet = self.net

        def chain(prefix):
            out = []
            for i in range(len(net.hiddens)):
                layer = p[f"{prefix}_{i}"]
                out.append(
                    (np.asarray(layer["kernel"]), np.asarray(layer["bias"]))
                )
            return out

        weights = {
            "pi": chain("pi"),
            "vf": None if net.vf_share_layers else chain("vf"),
            "pi_head": (
                np.asarray(p["pi_head"]["kernel"]),
                np.asarray(p["pi_head"]["bias"]),
            ),
            "vf_head": (
                np.asarray(p["vf_head"]["kernel"]),
                np.asarray(p["vf_head"]["bias"]),
            ),
            "act": {
                "tanh": np.tanh,
                "relu": lambda x: np.maximum(x, 0.0),
                "swish": lambda x: x / (1.0 + np.exp(-x)),
            }[net.activation],
        }
        self._np_weight_cache = (self.params, weights)
        return weights

    def _np_explore(self, obs: "np.ndarray", rng: "np.random.Generator") -> dict:
        from ray_tpu.rllib.core.distributions import Categorical

        w = self._np_weights()
        act = w["act"]
        x = obs.reshape(obs.shape[0], -1)
        z = x
        for kernel, bias in w["pi"]:
            z = act(z @ kernel + bias)
        pi_out = z @ w["pi_head"][0] + w["pi_head"][1]
        if w["vf"] is None:
            zv = z
        else:
            zv = x
            for kernel, bias in w["vf"]:
                zv = act(zv @ kernel + bias)
        vf = (zv @ w["vf_head"][0] + w["vf_head"][1])[:, 0]
        if self.dist_cls is Categorical:
            # Same normalization as distributions.Categorical so ACTION_LOGP
            # matches what the learner recomputes from ACTION_DIST_INPUTS.
            logits = pi_out - _np_logsumexp(pi_out)
            gumbel = -np.log(
                -np.log(rng.random(pi_out.shape, dtype=np.float64) + 1e-20)
            )
            actions = np.argmax(logits + gumbel, axis=-1)
            logp = np.take_along_axis(logits, actions[:, None], axis=-1)[:, 0]
        else:
            mean, log_std = np.split(pi_out, 2, axis=-1)
            std = np.exp(np.clip(log_std, -20.0, 2.0))
            actions = mean + std * rng.standard_normal(mean.shape).astype(
                mean.dtype
            )
            z_ = (actions - mean) / std
            logp = np.sum(
                -0.5 * z_**2 - np.log(std) - 0.5 * np.log(2.0 * np.pi), axis=-1
            )
        return {
            SampleBatch.ACTIONS: actions,
            SampleBatch.ACTION_LOGP: logp.astype(np.float32),
            SampleBatch.ACTION_DIST_INPUTS: pi_out.astype(np.float32),
            SampleBatch.VF_PREDS: vf.astype(np.float32),
        }

    def np_value_fn(self) -> Optional[Callable]:
        """Pure-numpy V(s) companion to np_exploration_fn (bootstrap
        values at truncations/fragment cuts)."""
        if self.np_exploration_fn() is None:
            return None

        def value(obs: "np.ndarray") -> "np.ndarray":
            w = self._np_weights()
            act = w["act"]
            x = obs.reshape(obs.shape[0], -1)
            z = x
            chain = w["pi"] if w["vf"] is None else w["vf"]
            for kernel, bias in chain:
                z = act(z @ kernel + bias)
            return (z @ w["vf_head"][0] + w["vf_head"][1])[:, 0].astype(
                np.float32
            )

        return value

    # -- state ------------------------------------------------------------

    def get_state(self) -> Any:
        return jax.device_get(self.params)

    def set_state(self, params: Any) -> None:
        self.params = params
        self._np_weight_cache = None


@dataclasses.dataclass
class RLModuleSpec:
    """Serializable recipe for building an RLModule on a remote worker
    (reference: SingleAgentRLModuleSpec, rl_module.py)."""

    module_class: type = RLModule
    observation_space: Optional[Space] = None
    action_space: Optional[Space] = None
    model_config: Optional[dict] = None
    net_builder: Optional[Callable[[], nn.Module]] = None
    seed: int = 0

    def build(self) -> RLModule:
        net = self.net_builder() if self.net_builder else None
        return self.module_class(
            self.observation_space,
            self.action_space,
            model_config=self.model_config,
            net=net,
            seed=self.seed,
        )


class MultiAgentRLModule:
    """{module_id: RLModule} container (reference: marl_module.py)."""

    def __init__(self, modules: Mapping[str, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def get_state(self) -> dict:
        return {mid: m.get_state() for mid, m in self._modules.items()}

    def set_state(self, state: Mapping) -> None:
        for mid, s in state.items():
            self._modules[mid].set_state(s)
