"""LearnerGroup — local learner or N learner actors with gradient averaging.

Reference: rllib/core/learner/learner_group.py:61. Two modes mirroring the
reference's `num_learners == 0` (local) vs `>= 1` (remote actors):

* local: one Learner in-process; on TPU hardware it jits over the host's mesh
  (`dp` axis), which already covers every chip of a slice — the common case.
* remote: N learner actors, each building the same Learner; a train batch is
  sharded across them, each computes gradients, the group tree-averages the
  gradients through the object store and applies them everywhere. This is the
  DCN path (multi-slice) where a single jitted program can't span processes —
  the re-design of the reference's DDP-wrapped learner actors
  (torch_learner.py:259).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.policy.sample_batch import SampleBatch


@ray_tpu.remote
class _LearnerActor:
    def __init__(self, learner_builder):
        self.learner = learner_builder()
        self.learner.build()

    def update(self, batch):
        return self.learner.update(batch)

    def compute_gradients(self, batch):
        return self.learner.compute_gradients(batch)

    def apply_gradients(self, grads):
        self.learner.apply_gradients(grads)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        self.learner.set_state(state)

    def call(self, method: str, *args):
        """Generic dispatch for learner-subclass methods (target syncs etc.)."""
        return getattr(self.learner, method)(*args)


def _average_grads(grad_list):
    return jax.tree_util.tree_map(
        lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), axis=0), *grad_list
    )


class LearnerGroup:
    def __init__(
        self,
        learner_builder: Callable,
        num_learners: int = 0,
        num_cpus_per_learner: float = 1,
        num_tpus_per_learner: float = 0,
        slice_unit: int = 1,
    ):
        self._num_learners = num_learners
        # Batch rows come in groups of `slice_unit` that must not be split
        # across learners (IMPALA fragments of rollout_fragment_length rows).
        self._slice_unit = max(1, int(slice_unit))
        self._workers = []
        self._local = None
        if num_learners == 0:
            self._local = learner_builder()
            self._local.build()
        else:
            opts = {"num_cpus": num_cpus_per_learner}
            if num_tpus_per_learner:
                opts["resources"] = {"TPU": num_tpus_per_learner}
            self._workers = [
                _LearnerActor.options(**opts).remote(learner_builder)
                for _ in range(num_learners)
            ]

    @property
    def is_local(self) -> bool:
        return self._local is not None

    @property
    def local_learner(self):
        return self._local

    def update(self, batch: SampleBatch) -> dict:
        if self.is_local:
            return self._local.update(batch)
        # Shard the batch across learners on slice_unit boundaries;
        # grad-average; apply everywhere. Units distribute round-robin so no
        # learner ever receives an empty shard (empty batches mean NaN
        # means that would poison the gradient average).
        n = len(self._workers)
        unit = self._slice_unit
        num_units = batch.count // unit
        if num_units == 0:
            shards = [batch]  # smaller than one unit: single learner
        else:
            shards = []
            start = 0
            for i in range(min(n, num_units)):
                take = num_units // n + (1 if i < num_units % n else 0)
                end = start + take * unit
                # Partial-unit tail rows (count % unit) are dropped — they
                # would break the fragment reshape in order-dependent losses.
                shards.append(batch.slice(start, end))
                start = end
        workers = self._workers[: len(shards)]
        results = ray_tpu.get(
            [w.compute_gradients.remote(s) for w, s in zip(workers, shards)]
        )
        grads = _average_grads([g for g, _ in results])
        ray_tpu.get([w.apply_gradients.remote(grads) for w in self._workers])
        metric_dicts = [m for _, m in results]
        out = {}
        for k in metric_dicts[0]:
            vals = [m[k] for m in metric_dicts]
            if np.ndim(vals[0]) == 0:
                out[k] = float(np.mean(vals))
            else:
                # Per-sample diagnostics (td errors) concatenate in shard
                # order, which matches the batch's row order.
                out[k] = np.concatenate([np.asarray(v) for v in vals])
        return out

    def foreach_learner(self, method: str, *args) -> list:
        """Call a learner-subclass method on every learner (public dispatch;
        algorithms must not reach into _local/_workers)."""
        if self.is_local:
            return [getattr(self._local, method)(*args)]
        return ray_tpu.get([w.call.remote(method, *args) for w in self._workers])

    def get_weights(self) -> Any:
        if self.is_local:
            return self._local.get_weights()
        return ray_tpu.get(self._workers[0].get_weights.remote())

    def set_weights(self, weights: Any) -> None:
        if self.is_local:
            self._local.set_weights(weights)
        else:
            ray_tpu.get([w.set_weights.remote(weights) for w in self._workers])

    def get_state(self) -> dict:
        if self.is_local:
            return self._local.get_state()
        return ray_tpu.get(self._workers[0].get_state.remote())

    def set_state(self, state: Any) -> None:
        if self.is_local:
            self._local.set_state(state)
        else:
            ray_tpu.get([w.set_state.remote(state) for w in self._workers])

    def shutdown(self) -> None:
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = []
