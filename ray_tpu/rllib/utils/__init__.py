from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)

__all__ = ["PrioritizedReplayBuffer", "ReplayBuffer"]
