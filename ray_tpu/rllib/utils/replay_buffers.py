"""Replay buffers — uniform ring + proportional prioritized.

Reference: rllib/utils/replay_buffers/replay_buffer.py (ReplayBuffer,
storage_unit=timesteps) and prioritized_replay_buffer.py (proportional
prioritization per Schaul et al.; the reference uses a segment tree — numpy
cumulative sums are equivalent at the sizes that fit one host and keep the
sampling path vectorized).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform-sampling ring buffer over timestep rows."""

    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = int(capacity)
        self._columns: dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)
        self._num_added = 0

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if n == 0:
            return
        self._num_added += n
        for k, v in batch.items():
            if k == SampleBatch.INFOS:
                continue
            v = np.asarray(v)
            if k not in self._columns:
                self._columns[k] = np.zeros(
                    (self.capacity,) + v.shape[1:], dtype=v.dtype
                )
            col = self._columns[k]
            idx = (self._next + np.arange(n)) % self.capacity
            col[idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, num_items: int) -> SampleBatch:
        assert self._size > 0, "buffer empty"
        idx = self._rng.integers(0, self._size, size=num_items)
        return self._take(idx)

    def _take(self, idx: np.ndarray) -> SampleBatch:
        return SampleBatch({k: v[idx] for k, v in self._columns.items()})

    def stats(self) -> dict:
        return {"size": self._size, "num_added": self._num_added}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized sampling with importance weights."""

    def __init__(
        self,
        capacity: int = 100_000,
        alpha: float = 0.6,
        beta: float = 0.4,
        seed: Optional[int] = None,
    ):
        super().__init__(capacity, seed)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self._priorities = np.zeros(self.capacity, dtype=np.float64)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        idx = (self._next + np.arange(n)) % self.capacity
        super().add(batch)
        self._priorities[idx] = self._max_priority**self.alpha

    def sample(self, num_items: int, beta: Optional[float] = None) -> SampleBatch:
        assert self._size > 0, "buffer empty"
        beta = self.beta if beta is None else beta
        p = self._priorities[: self._size]
        probs = p / p.sum()
        idx = self._rng.choice(self._size, size=num_items, p=probs)
        batch = self._take(idx)
        weights = (self._size * probs[idx]) ** (-beta)
        batch["weights"] = (weights / weights.max()).astype(np.float32)
        batch["batch_indexes"] = idx.astype(np.int64)
        return batch

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray) -> None:
        priorities = np.abs(np.asarray(priorities, dtype=np.float64)) + 1e-6
        self._priorities[idx] = priorities**self.alpha
        self._max_priority = max(self._max_priority, float(priorities.max()))
