"""Exploration schedules and action-noise helpers.

Reference: rllib/utils/exploration/ (EpsilonGreedy, GaussianNoise,
OrnsteinUhlenbeckNoise, schedules in rllib/utils/schedules/). TPU-native
framing: exploration STATE (the schedule position) is host-side and enters
the jitted `forward_exploration` as a traced scalar via the module's
`exploration_inputs(timestep)` hook — annealing never retraces.

Modules compose these instead of hand-rolling schedules (dqn.py's inline
epsilon schedule now delegates here).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np


@dataclass
class LinearSchedule:
    """value(t): initial -> final over `timesteps`, then flat."""

    initial: float
    final: float
    timesteps: int

    def value(self, t: int) -> float:
        frac = min(1.0, t / max(1, self.timesteps))
        return self.initial + frac * (self.final - self.initial)


@dataclass
class ExponentialSchedule:
    """value(t) = initial * decay_rate^(t / timesteps), floored at final."""

    initial: float
    final: float
    timesteps: int
    decay_rate: float = 0.1

    def value(self, t: int) -> float:
        v = self.initial * self.decay_rate ** (t / max(1, self.timesteps))
        return max(self.final, v)


@dataclass
class EpsilonGreedy:
    """Epsilon schedule for discrete action spaces; the module merges
    {'epsilon': eps(t)} into the exploration batch and mixes random actions
    in its jitted forward (dqn.py's pattern)."""

    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_timesteps: int = 10_000
    schedule: str = "linear"  # or "exponential"

    def epsilon(self, timestep: int) -> float:
        if self.schedule == "exponential":
            return ExponentialSchedule(
                self.epsilon_initial, self.epsilon_final, self.epsilon_timesteps
            ).value(timestep)
        return LinearSchedule(
            self.epsilon_initial, self.epsilon_final, self.epsilon_timesteps
        ).value(timestep)

    def inputs(self, timestep: int) -> dict:
        return {"epsilon": np.float32(self.epsilon(timestep))}


@dataclass
class GaussianNoise:
    """Additive Gaussian action noise for continuous spaces, with an
    annealed scale (reference: exploration/gaussian_noise.py). Use
    `inputs()` for the traced scale and `apply()` for host-side numpy
    policies."""

    initial_scale: float = 1.0
    final_scale: float = 0.1
    scale_timesteps: int = 10_000
    clip: float | None = None

    def scale(self, timestep: int) -> float:
        return LinearSchedule(
            self.initial_scale, self.final_scale, self.scale_timesteps
        ).value(timestep)

    def inputs(self, timestep: int) -> dict:
        return {"noise_scale": np.float32(self.scale(timestep))}

    def apply(self, actions: np.ndarray, timestep: int,
              rng: np.random.Generator) -> np.ndarray:
        noisy = actions + rng.normal(
            0.0, self.scale(timestep), size=actions.shape
        )
        if self.clip is not None:
            noisy = np.clip(noisy, -self.clip, self.clip)
        return noisy.astype(actions.dtype, copy=False)


@dataclass
class OrnsteinUhlenbeckNoise:
    """Temporally-correlated noise for continuous control (reference:
    exploration/ornstein_uhlenbeck_noise.py). Stateful: call reset() at
    episode boundaries."""

    theta: float = 0.15
    sigma: float = 0.2
    dt: float = 1e-2

    def __post_init__(self):
        self._state: np.ndarray | None = None

    def reset(self) -> None:
        self._state = None

    def apply(self, actions: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        if self._state is None or self._state.shape != actions.shape:
            self._state = np.zeros_like(actions, dtype=np.float64)
        self._state = (
            self._state
            - self.theta * self._state * self.dt
            + self.sigma * math.sqrt(self.dt)
            * rng.normal(size=actions.shape)
        )
        return (actions + self._state).astype(actions.dtype, copy=False)
