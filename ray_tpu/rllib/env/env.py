"""Environment API: Env, VectorEnv, MultiAgentEnv.

Gymnasium step convention: `reset(seed) -> (obs, info)`,
`step(a) -> (obs, reward, terminated, truncated, info)`. The reference
vectorizes envs inside the sampler (rllib/env/vector_env.py VectorEnvWrapper);
here `SyncVectorEnv` is the only vectorization layer and auto-resets finished
sub-envs, which is what the batched rollout loop (env_runner.py) consumes —
fixed batch shapes every step, the XLA-friendly property.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.rllib.env.spaces import Space


class Env:
    """Single-agent environment base (reference: gym.Env as used throughout
    rllib/env/)."""

    observation_space: Space
    action_space: Space

    def reset(self, *, seed: Optional[int] = None) -> tuple:
        raise NotImplementedError

    def step(self, action) -> tuple:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MultiAgentEnv(Env):
    """Dict-keyed multi-agent env (reference: rllib/env/multi_agent_env.py).

    reset -> ({agent: obs}, {agent: info}); step({agent: action}) ->
    (obs_dict, rew_dict, terminated_dict, truncated_dict, info_dict) with the
    special "__all__" key in terminated/truncated.
    """

    agent_ids: list = []

    def observation_space_for(self, agent_id) -> Space:
        return self.observation_space

    def action_space_for(self, agent_id) -> Space:
        return self.action_space


class VectorEnv:
    """Natively-batched environment: all B sub-envs advance in ONE call.

    Reference: rllib/env/vector_env.py VectorEnv (the `vector_step` API).
    The python-loop SyncVectorEnv below costs ~10us of interpreter per
    sub-env per step; a numpy-vectorized implementation (classic.py
    VectorCartPole, minatar.py) steps hundreds of envs in one fused pass —
    on one sampling core that is the difference between 40k and 100k+
    env-steps/s. Must implement the same auto-reset contract as
    SyncVectorEnv: done sub-envs reset in place and surface the true final
    observation via infos[i]["final_observation"].
    """

    observation_space: Space
    action_space: Space
    num_envs: int

    def reset(self, *, seed: Optional[int] = None) -> tuple:
        raise NotImplementedError

    def step(self, actions) -> tuple:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SyncVectorEnv(VectorEnv):
    """N sub-envs stepped in lockstep with auto-reset.

    Reference: rllib/env/vector_env.py:_VectorizedGymEnv (vector_env.py, auto
    reset in VectorEnvWrapper). Terminal observations are replaced by the
    reset observation; the true final obs is surfaced in infos as
    "final_observation" (gymnasium convention) for bootstrap-value computation.
    """

    def __init__(self, env_fns: list):
        assert env_fns, "need at least one env"
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = [], []
        for i, env in enumerate(self.envs):
            o, info = env.reset(seed=None if seed is None else seed + i)
            obs.append(o)
            infos.append(info)
        return np.stack(obs), infos

    def step(self, actions):
        obs, rews, terms, truncs, infos = [], [], [], [], []
        for env, action in zip(self.envs, actions):
            o, r, term, trunc, info = env.step(action)
            if term or trunc:
                info = dict(info)
                info["final_observation"] = o
                o, _ = env.reset()
            obs.append(o)
            rews.append(r)
            terms.append(term)
            truncs.append(trunc)
            infos.append(info)
        return (
            np.stack(obs),
            np.asarray(rews, dtype=np.float32),
            np.asarray(terms, dtype=bool),
            np.asarray(truncs, dtype=bool),
            infos,
        )

    def close(self):
        for env in self.envs:
            env.close()


class EnvContext(dict):
    """Env config dict + worker/vector indices (reference:
    rllib/env/env_context.py)."""

    def __init__(self, config: dict, worker_index: int = 0, vector_index: int = 0):
        super().__init__(config or {})
        self.worker_index = worker_index
        self.vector_index = vector_index


_ENV_REGISTRY: dict[str, Callable[[EnvContext], Env]] = {}
_VECTOR_ENV_REGISTRY: dict[str, Callable[[int, EnvContext], "VectorEnv"]] = {}


def register_env(name: str, creator: Callable[[Any], Env]) -> None:
    """Reference: ray/tune/registry.py register_env as used by rllib."""
    _ENV_REGISTRY[name] = creator


def register_vector_env(
    name: str, creator: Callable[[int, EnvContext], "VectorEnv"]
) -> None:
    """Register a natively-batched implementation for an env name; the
    env runner prefers it over per-env SyncVectorEnv wrapping.
    creator(num_envs, ctx) -> VectorEnv."""
    _VECTOR_ENV_REGISTRY[name] = creator


class GymnasiumEnv(Env):
    """Adapter for gymnasium environments (reference:
    rllib/env/wrappers/atari_wrappers.py + the gym.make interop throughout
    rllib/env/utils.py): translates gymnasium spaces to ray_tpu spaces and
    passes the 5-tuple step convention through unchanged."""

    def __init__(self, gym_env):
        from ray_tpu.rllib.env.spaces import from_gymnasium

        self._env = gym_env
        self.observation_space = from_gymnasium(gym_env.observation_space)
        self.action_space = from_gymnasium(gym_env.action_space)

    def reset(self, *, seed: Optional[int] = None):
        return self._env.reset(seed=seed)

    def step(self, action):
        return self._env.step(action)

    def close(self) -> None:
        self._env.close()


def _ensure_builtins() -> None:
    from ray_tpu.rllib.env import classic, minatar  # noqa: F401 — register


def make_env(spec, config: Optional[dict] = None, worker_index: int = 0) -> Env:
    """Resolve an env spec: registered name, Env subclass, callable, or any
    gymnasium id (e.g. "LunarLander-v3") as a fallback."""
    ctx = EnvContext(config or {}, worker_index=worker_index)
    if isinstance(spec, str):
        if spec not in _ENV_REGISTRY:
            _ensure_builtins()
        if spec in _ENV_REGISTRY:
            return _ENV_REGISTRY[spec](ctx)
        try:
            import gymnasium

            return GymnasiumEnv(gymnasium.make(spec, **ctx))
        except Exception:
            raise KeyError(
                f"Unknown env {spec!r}; registered: {sorted(_ENV_REGISTRY)} "
                "(and not resolvable as a gymnasium id)"
            ) from None
    if isinstance(spec, type) and issubclass(spec, Env):
        try:
            return spec(ctx)
        except TypeError:
            return spec()
    if callable(spec):
        return spec(ctx)
    raise TypeError(f"Bad env spec: {spec!r}")


def make_vector_env(
    spec,
    num_envs: int,
    config: Optional[dict] = None,
    worker_index: int = 0,
) -> "VectorEnv":
    """Vectorize an env spec: a registered native VectorEnv when one exists
    (one fused numpy step for all sub-envs), else SyncVectorEnv around
    per-env instances."""
    if isinstance(spec, str):
        if spec not in _VECTOR_ENV_REGISTRY and spec not in _ENV_REGISTRY:
            _ensure_builtins()
        creator = _VECTOR_ENV_REGISTRY.get(spec)
        if creator is not None:
            ctx = EnvContext(config or {}, worker_index=worker_index)
            return creator(num_envs, ctx)
    return SyncVectorEnv(
        [
            (lambda i=i: make_env(spec, config, worker_index=worker_index))
            for i in range(num_envs)
        ]
    )
