"""MinAtar-style Breakout: the in-tree Atari-class benchmark environment.

BASELINE config #3 names "PPO + IMPALA on Atari"; the sealed image ships
neither ALE nor MinAtar, so the Atari-class path is carried in-tree as a
re-derivation of MinAtar Breakout's published game rules (10x10 grid,
binary channel planes, diagonal ball, one-cell paddle, three brick rows —
the standard miniaturized-Atari testbed): image-shaped observations
[10, 10, 4], sparse rewards, and a control problem that separates learning
algorithms the way full Atari does, at a scale CPU sampling hosts sustain.
Gymnasium's real ALE plugs in through env.GymnasiumEnv when installed
(reference: rllib/env/wrappers/atari_wrappers.py).

Implemented natively vectorized: all B boards advance in one numpy pass
(state arrays [B, ...]), the same fused-step design as
classic.VectorCartPole. The single-env class wraps the vector one at B=1.

Channels: 0=paddle, 1=ball, 2=ball trail (previous position), 3=bricks.
Actions: 0=noop, 1=left, 2=right. Reward +1 per brick. Episode ends when
the ball passes the paddle (or at max_steps truncation); clearing the wall
respawns it.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.env.env import (
    Env,
    VectorEnv,
    register_env,
    register_vector_env,
)
from ray_tpu.rllib.env.spaces import Box, Discrete

GRID = 10
BRICK_ROWS = (1, 2, 3)
MAX_STEPS = 1000


class VectorMinAtarBreakout(VectorEnv):
    def __init__(self, num_envs: int, config: Optional[dict] = None):
        config = config or {}
        self.num_envs = int(num_envs)
        self.max_steps = int(config.get("max_steps", MAX_STEPS))
        # Sticky actions (MinAtar's difficulty knob): with prob p the
        # previous action repeats.
        self.sticky_prob = float(config.get("sticky_action_prob", 0.1))
        self.observation_space = Box(0.0, 1.0, shape=(GRID, GRID, 4))
        self.action_space = Discrete(3)
        self._rng = np.random.default_rng()
        B = self.num_envs
        self._ball = np.zeros((B, 2), dtype=np.int64)  # (y, x)
        self._vel = np.zeros((B, 2), dtype=np.int64)
        self._trail = np.zeros((B, 2), dtype=np.int64)
        self._paddle = np.zeros(B, dtype=np.int64)
        self._bricks = np.zeros((B, len(BRICK_ROWS), GRID), dtype=bool)
        self._steps = np.zeros(B, dtype=np.int64)
        self._last_action = np.zeros(B, dtype=np.int64)

    # -- state helpers ------------------------------------------------------

    def _spawn(self, idx: np.ndarray) -> None:
        n = len(idx)
        self._ball[idx, 0] = 0
        self._ball[idx, 1] = self._rng.integers(0, GRID, size=n)
        self._vel[idx, 0] = 1
        self._vel[idx, 1] = self._rng.choice((-1, 1), size=n)
        self._trail[idx] = self._ball[idx]
        self._paddle[idx] = GRID // 2
        self._bricks[idx] = True
        self._steps[idx] = 0
        self._last_action[idx] = 0

    def _obs(self) -> np.ndarray:
        B = self.num_envs
        obs = np.zeros((B, GRID, GRID, 4), dtype=np.float32)
        rows = np.arange(B)
        obs[rows, GRID - 1, self._paddle, 0] = 1.0
        obs[rows, self._ball[:, 0], self._ball[:, 1], 1] = 1.0
        obs[rows, self._trail[:, 0], self._trail[:, 1], 2] = 1.0
        for ci, row in enumerate(BRICK_ROWS):
            obs[:, row, :, 3] = self._bricks[:, ci]
        return obs

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._spawn(np.arange(self.num_envs))
        return self._obs(), [{} for _ in range(self.num_envs)]

    def step(self, actions):
        B = self.num_envs
        actions = np.asarray(actions).astype(np.int64).reshape(B)
        sticky = self._rng.random(B) < self.sticky_prob
        actions = np.where(sticky, self._last_action, actions)
        self._last_action = actions

        # Paddle move.
        self._paddle = np.clip(
            self._paddle + np.where(actions == 1, -1, 0) + np.where(actions == 2, 1, 0),
            0,
            GRID - 1,
        )

        rewards = np.zeros(B, dtype=np.float32)
        # Ball advance with wall bounces (x), ceiling bounce (y).
        new_x = self._ball[:, 1] + self._vel[:, 1]
        bounce_x = (new_x < 0) | (new_x >= GRID)
        self._vel[:, 1] = np.where(bounce_x, -self._vel[:, 1], self._vel[:, 1])
        new_x = np.clip(new_x, 0, GRID - 1)
        new_y = self._ball[:, 0] + self._vel[:, 0]
        bounce_y = new_y < 0
        self._vel[:, 0] = np.where(bounce_y, -self._vel[:, 0], self._vel[:, 0])
        new_y = np.abs(new_y)

        # Brick hits: remove the brick, score, reflect vertically (the ball
        # does not enter the brick cell this step).
        hit = np.zeros(B, dtype=bool)
        for ci, row in enumerate(BRICK_ROWS):
            at_row = new_y == row
            has_brick = self._bricks[np.arange(B), ci, new_x]
            h = at_row & has_brick
            if h.any():
                self._bricks[np.nonzero(h)[0], ci, new_x[h]] = False
                hit |= h
        rewards += hit.astype(np.float32)
        self._vel[:, 0] = np.where(hit, -self._vel[:, 0], self._vel[:, 0])
        new_y = np.where(hit, self._ball[:, 0], new_y)

        # Bottom row: paddle saves (reflect), otherwise the ball is lost.
        at_bottom = new_y >= GRID - 1
        saved = at_bottom & (new_x == self._paddle)
        terminated = at_bottom & ~saved
        self._vel[:, 0] = np.where(saved, -1, self._vel[:, 0])
        new_y = np.where(saved, GRID - 2, new_y)
        new_y = np.where(terminated, GRID - 1, new_y)

        self._trail = self._ball.copy()
        self._ball = np.stack([new_y, new_x], axis=1)

        # Cleared wall: respawn bricks (play continues — MinAtar behavior).
        cleared = ~self._bricks.any(axis=(1, 2))
        if cleared.any():
            self._bricks[cleared] = True

        self._steps += 1
        truncated = (~terminated) & (self._steps >= self.max_steps)
        obs = self._obs()
        done = terminated | truncated
        infos: list = [{}] * B
        if done.any():
            idx = np.nonzero(done)[0]
            infos = [{} for _ in range(B)]
            for i in idx:
                infos[i] = {"final_observation": obs[i].copy()}
            self._spawn(idx)
            fresh = self._obs()
            obs[idx] = fresh[idx]
        return obs, rewards, terminated, truncated, infos


class MinAtarBreakout(Env):
    """Single-env wrapper over the vectorized implementation (B=1)."""

    def __init__(self, config: Optional[dict] = None):
        self._vec = VectorMinAtarBreakout(1, config)
        self.observation_space = self._vec.observation_space
        self.action_space = self._vec.action_space

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = self._vec.reset(seed=seed)
        return obs[0], infos[0]

    def step(self, action):
        obs, rew, term, trunc, infos = self._vec.step(np.array([action]))
        return obs[0], float(rew[0]), bool(term[0]), bool(trunc[0]), infos[0]


register_env("MinAtar-Breakout", lambda cfg: MinAtarBreakout(cfg))
register_vector_env(
    "MinAtar-Breakout", lambda n, cfg: VectorMinAtarBreakout(n, cfg)
)
