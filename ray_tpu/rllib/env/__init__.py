from ray_tpu.rllib.env.env import (
    Env,
    EnvContext,
    GymnasiumEnv,
    MultiAgentEnv,
    SyncVectorEnv,
    VectorEnv,
    make_env,
    make_vector_env,
    register_env,
    register_vector_env,
)
from ray_tpu.rllib.env.spaces import Box, Discrete, Space, flat_dim

__all__ = [
    "Box",
    "Discrete",
    "Env",
    "EnvContext",
    "GymnasiumEnv",
    "MultiAgentEnv",
    "Space",
    "SyncVectorEnv",
    "VectorEnv",
    "flat_dim",
    "make_env",
    "make_vector_env",
    "register_env",
    "register_vector_env",
]
