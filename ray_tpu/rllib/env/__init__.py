from ray_tpu.rllib.env.env import (
    Env,
    EnvContext,
    MultiAgentEnv,
    SyncVectorEnv,
    make_env,
    register_env,
)
from ray_tpu.rllib.env.spaces import Box, Discrete, Space, flat_dim

__all__ = [
    "Box",
    "Discrete",
    "Env",
    "EnvContext",
    "MultiAgentEnv",
    "Space",
    "SyncVectorEnv",
    "flat_dim",
    "make_env",
    "register_env",
]
