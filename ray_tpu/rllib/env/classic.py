"""Classic-control environments in numpy.

The image ships no gym, so the benchmark/test envs live in-tree. Dynamics
follow the standard OpenAI Gym formulations (CartPole-v1, Pendulum-v1) that
the reference's tuned examples train against (rllib/tuned_examples/ppo/
cartpole-ppo.yaml etc.) so learning-curve expectations transfer.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.env.env import (
    Env,
    MultiAgentEnv,
    VectorEnv,
    register_env,
    register_vector_env,
)
from ray_tpu.rllib.env.spaces import Box, Discrete


class CartPole(Env):
    """Pole balancing; episode ends past ±12° / ±2.4m / 500 steps."""

    MAX_STEPS = 500

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.length = 0.5  # half pole length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        high = np.array(
            [self.x_threshold * 2, np.finfo(np.float32).max,
             self.theta_threshold * 2, np.finfo(np.float32).max],
            dtype=np.float32,
        )
        self.observation_space = Box(-high, high)
        self.action_space = Discrete(2)
        self.max_steps = int(config.get("max_steps", self.MAX_STEPS))
        self._rng = np.random.default_rng()
        self._state = None
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self._steps = 0
        return self._state.copy(), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.force_mag if int(action) == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x += self.tau * x_dot
        x_dot += self.tau * xacc
        theta += self.tau * theta_dot
        theta_dot += self.tau * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot], dtype=np.float32)
        self._steps += 1
        terminated = bool(
            abs(x) > self.x_threshold or abs(theta) > self.theta_threshold
        )
        truncated = self._steps >= self.max_steps
        return self._state.copy(), 1.0, terminated, truncated, {}


class Pendulum(Env):
    """Swing-up with continuous torque; reward = -(angle² + .1ω² + .001u²)."""

    MAX_STEPS = 200

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.max_speed, self.max_torque = 8.0, 2.0
        self.dt, self.g, self.m, self.l = 0.05, 10.0, 1.0, 1.0
        high = np.array([1.0, 1.0, self.max_speed], dtype=np.float32)
        self.observation_space = Box(-high, high)
        self.action_space = Box(-self.max_torque, self.max_torque, shape=(1,))
        self.max_steps = int(config.get("max_steps", self.MAX_STEPS))
        self._rng = np.random.default_rng()
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._theta = self._rng.uniform(-np.pi, np.pi)
        self._theta_dot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs(), {}

    def _obs(self):
        return np.array(
            [np.cos(self._theta), np.sin(self._theta), self._theta_dot],
            dtype=np.float32,
        )

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.max_torque, self.max_torque))
        th, thdot = self._theta, self._theta_dot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (
            3 * self.g / (2 * self.l) * np.sin(th) + 3.0 / (self.m * self.l**2) * u
        ) * self.dt
        thdot = float(np.clip(thdot, -self.max_speed, self.max_speed))
        self._theta = th + thdot * self.dt
        self._theta_dot = thdot
        self._steps += 1
        return self._obs(), -float(cost), False, self._steps >= self.max_steps, {}


class RandomEnv(Env):
    """Uniform-random rewards/observations; throughput benchmarking env
    (reference: rllib/examples/env/random_env.py, used by sampler perf tests)."""

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.observation_space = config.get("observation_space") or Box(
            -1.0, 1.0, shape=(int(config.get("obs_dim", 4)),)
        )
        self.action_space = config.get("action_space") or Discrete(2)
        self.episode_len = int(config.get("episode_len", 100))
        self._rng = np.random.default_rng()
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._steps = 0
        return self.observation_space.sample(self._rng), {}

    def step(self, action):
        self._steps += 1
        return (
            self.observation_space.sample(self._rng),
            float(self._rng.random()),
            False,
            self._steps >= self.episode_len,
            {},
        )


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPoles under one multi-agent env (reference:
    rllib/examples/env/multi_agent.py MultiAgentCartPole)."""

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.num_agents = int(config.get("num_agents", 2))
        self.agent_ids = [f"agent_{i}" for i in range(self.num_agents)]
        self._envs = {aid: CartPole(config) for aid in self.agent_ids}
        self._done = {aid: False for aid in self.agent_ids}
        first = self._envs[self.agent_ids[0]]
        self.observation_space = first.observation_space
        self.action_space = first.action_space

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = {}, {}
        for i, (aid, env) in enumerate(self._envs.items()):
            o, info = env.reset(seed=None if seed is None else seed + i)
            obs[aid], infos[aid] = o, info
            self._done[aid] = False
        return obs, infos

    def step(self, action_dict):
        obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
        for aid, action in action_dict.items():
            if self._done[aid]:
                continue
            o, r, term, trunc, info = self._envs[aid].step(action)
            obs[aid], rews[aid] = o, r
            terms[aid], truncs[aid], infos[aid] = term, trunc, info
            if term or trunc:
                self._done[aid] = True
        terms["__all__"] = all(self._done.values())
        truncs["__all__"] = False
        return obs, rews, terms, truncs, infos


class VectorCartPole(VectorEnv):
    """All B cartpoles advanced in one fused numpy pass (state [B,4]).

    Same dynamics/termination as CartPole above; the auto-reset contract
    matches SyncVectorEnv (done rows reset in place, true final obs in
    infos[i]["final_observation"]). ~20x less interpreter overhead per
    env-step than stepping B python envs — the sampler-throughput win the
    reference gets from its remote vector envs, obtained by vectorizing
    the math instead."""

    def __init__(self, num_envs: int, config: Optional[dict] = None):
        config = config or {}
        proto = CartPole(config)
        self.observation_space = proto.observation_space
        self.action_space = proto.action_space
        self.num_envs = int(num_envs)
        self.max_steps = proto.max_steps
        self.theta_threshold = proto.theta_threshold
        self.x_threshold = proto.x_threshold
        self.force_mag = proto.force_mag
        self.tau = proto.tau
        self.gravity = proto.gravity
        self.masscart, self.masspole = proto.masscart, proto.masspole
        self.length = proto.length
        self._rng = np.random.default_rng()
        self._state = np.zeros((self.num_envs, 4), dtype=np.float32)
        self._steps = np.zeros(self.num_envs, dtype=np.int64)

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4)).astype(np.float32)

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._sample_state(self.num_envs)
        self._steps[:] = 0
        return self._state.copy(), [{} for _ in range(self.num_envs)]

    def step(self, actions):
        s = self._state
        x, x_dot, theta, theta_dot = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        force = np.where(
            np.asarray(actions).astype(np.int64) == 1,
            self.force_mag,
            -self.force_mag,
        ).astype(np.float32)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        state = np.stack([x, x_dot, theta, theta_dot], axis=1).astype(np.float32)
        self._steps += 1
        terminated = (np.abs(x) > self.x_threshold) | (
            np.abs(theta) > self.theta_threshold
        )
        truncated = (~terminated) & (self._steps >= self.max_steps)
        rewards = np.ones(self.num_envs, dtype=np.float32)
        done = terminated | truncated
        infos: list = [{}] * self.num_envs
        if done.any():
            idx = np.nonzero(done)[0]
            infos = [{} for _ in range(self.num_envs)]
            for i in idx:
                infos[i] = {"final_observation": state[i].copy()}
            state[idx] = self._sample_state(len(idx))
            self._steps[idx] = 0
        self._state = state
        return state.copy(), rewards, terminated, truncated, infos


register_env("CartPole-v1", lambda cfg: CartPole(cfg))
register_env("Pendulum-v1", lambda cfg: Pendulum(cfg))
register_env("RandomEnv", lambda cfg: RandomEnv(cfg))
register_env("MultiAgentCartPole", lambda cfg: MultiAgentCartPole(cfg))
register_vector_env(
    "CartPole-v1", lambda n, cfg: VectorCartPole(n, cfg)
)
