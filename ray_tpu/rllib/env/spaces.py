"""Observation/action spaces.

The image ships no gym/gymnasium, so rllib carries its own minimal space
algebra with the gymnasium calling convention (`sample`, `contains`, `shape`,
`dtype`, `n`). Reference envs type against gym.spaces (rllib/env/*); anything
written for gymnasium's Box/Discrete maps 1:1 onto these.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


class Space:
    shape: Tuple[int, ...] = ()
    dtype: np.dtype = np.float32

    def sample(self, rng: Optional[np.random.Generator] = None):
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError


class Box(Space):
    def __init__(self, low, high, shape: Optional[Sequence[int]] = None, dtype=np.float32):
        if shape is None:
            shape = np.broadcast(np.asarray(low), np.asarray(high)).shape
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.low = np.broadcast_to(np.asarray(low, dtype=self.dtype), self.shape)
        self.high = np.broadcast_to(np.asarray(high, dtype=self.dtype), self.shape)

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        low = np.where(np.isfinite(self.low), self.low, -1.0)
        high = np.where(np.isfinite(self.high), self.high, 1.0)
        return rng.uniform(low, high, size=self.shape).astype(self.dtype)

    def contains(self, x) -> bool:
        x = np.asarray(x)
        return x.shape == self.shape and bool(
            np.all(x >= self.low) and np.all(x <= self.high)
        )

    def __repr__(self):
        return f"Box{self.shape}"


class Discrete(Space):
    def __init__(self, n: int):
        self.n = int(n)
        self.shape = ()
        self.dtype = np.dtype(np.int32)

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        return int(rng.integers(self.n))

    def contains(self, x) -> bool:
        return 0 <= int(x) < self.n

    def __repr__(self):
        return f"Discrete({self.n})"


def flat_dim(space: Space) -> int:
    """Size of the flattened observation / logits dim for an action space."""
    if isinstance(space, Discrete):
        return space.n
    return int(np.prod(space.shape)) if space.shape else 1


def from_gymnasium(space) -> Space:
    """Translate a gymnasium space into the in-tree algebra (the adapter
    half of env.GymnasiumEnv)."""
    name = type(space).__name__
    if name == "Discrete":
        return Discrete(int(space.n))
    if name == "Box":
        return Box(space.low, space.high, shape=space.shape, dtype=space.dtype)
    raise TypeError(f"Unsupported gymnasium space: {space!r}")
