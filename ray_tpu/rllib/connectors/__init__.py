"""Connectors — observation preprocessing between env and module.

Reference: rllib/connectors/ (agent connector pipelines) + utils/filter.py
(MeanStdFilter with distributed stat sync). The high-value member is running
mean-std observation normalization: each runner updates local Welford stats
while sampling, the algorithm merges per-runner deltas into a global stat at
weight-sync time and broadcasts it back, so every runner (and the serving
path) normalizes identically.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


class RunningStat:
    """Parallel-mergeable Welford accumulator over feature vectors."""

    def __init__(self, shape: Sequence[int] = ()):
        self.shape = tuple(shape)
        self.count = 0.0
        self.mean = np.zeros(self.shape, np.float64)
        self.m2 = np.zeros(self.shape, np.float64)

    def push_batch(self, x: np.ndarray) -> None:
        x = np.asarray(x, np.float64).reshape((-1,) + self.shape)
        n_b = x.shape[0]
        if n_b == 0:
            return
        mean_b = x.mean(axis=0)
        m2_b = ((x - mean_b) ** 2).sum(axis=0)
        self._merge(n_b, mean_b, m2_b)

    def _merge(self, n_b: float, mean_b, m2_b) -> None:
        n_a = self.count
        n = n_a + n_b
        delta = mean_b - self.mean
        self.mean = self.mean + delta * (n_b / n)
        self.m2 = self.m2 + m2_b + delta**2 * (n_a * n_b / n)
        self.count = n

    def merge(self, other: "RunningStat") -> None:
        if other.count > 0:
            self._merge(other.count, other.mean, other.m2)

    @property
    def std(self) -> np.ndarray:
        if self.count < 2:
            return np.ones(self.shape, np.float64)
        return np.sqrt(np.maximum(self.m2 / (self.count - 1), 1e-8))

    def copy(self) -> "RunningStat":
        out = RunningStat(self.shape)
        out.count, out.mean, out.m2 = self.count, self.mean.copy(), self.m2.copy()
        return out

    def to_state(self) -> dict:
        return {"count": self.count, "mean": self.mean, "m2": self.m2,
                "shape": self.shape}

    @classmethod
    def from_state(cls, state: dict) -> "RunningStat":
        out = cls(state["shape"])
        out.count = state["count"]
        out.mean = np.asarray(state["mean"], np.float64)
        out.m2 = np.asarray(state["m2"], np.float64)
        return out


class MeanStdFilter:
    """Normalizes observations to ~N(0,1) with running stats.

    Tracks a `delta` accumulator of everything pushed since the last flush,
    so the driver can merge per-runner deltas into the authoritative global
    stat without double counting (reference: utils/filter.py apply_changes)."""

    def __init__(self, shape: Sequence[int]):
        self.stat = RunningStat(shape)
        self.delta = RunningStat(shape)

    def __call__(self, x: np.ndarray, update: bool = True) -> np.ndarray:
        if update:
            self.stat.push_batch(x)
            self.delta.push_batch(x)
        return ((np.asarray(x, np.float64) - self.stat.mean) / self.stat.std).astype(
            np.float32
        )

    def flush_delta(self) -> dict:
        delta = self.delta
        self.delta = RunningStat(self.stat.shape)
        return delta.to_state()

    def set_global(self, state: dict) -> None:
        self.stat = RunningStat.from_state(state)

    def get_state(self) -> dict:
        return self.stat.to_state()


def make_observation_filter(name: Optional[str], obs_shape) -> Optional[MeanStdFilter]:
    if not name or name == "NoFilter":
        return None
    if name == "MeanStdFilter":
        return MeanStdFilter(tuple(obs_shape))
    raise ValueError(f"Unknown observation filter {name!r}")


__all__ = ["MeanStdFilter", "RunningStat", "make_observation_filter"]
