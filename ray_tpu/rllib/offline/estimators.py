"""Off-policy estimators: evaluate a target policy on logged behavior data.

Reference: rllib/offline/off_policy_estimator.py (+ estimators/
importance_sampling.py, weighted_importance_sampling.py). Logged batches
must carry the behavior policy's action log-probs (SampleBatch.ACTION_LOGP,
recorded by every exploration forward here) and episode ids; the estimator
scores a TARGET policy via `target_logp_fn(obs, actions) -> logp` without
running it in the environment:

  * IS  — per-episode cumulative importance ratios weight the rewards
          (unbiased, high variance);
  * WIS — ratios are normalized by their per-timestep population mean
          (biased, much lower variance; the reference's default).

Both also report V_behavior (the logged returns) so improvement is read
directly from the gap.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class OffPolicyEstimator:
    """Base: accumulate per-episode estimates over logged batches."""

    def __init__(
        self,
        target_logp_fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
        gamma: float = 0.99,
        logp_clip: float = 20.0,
    ):
        self.target_logp_fn = target_logp_fn
        self.gamma = gamma
        self.logp_clip = logp_clip
        self._episodes: List[dict] = []

    # -- accumulation -------------------------------------------------------

    def process(self, batch: SampleBatch) -> None:
        if SampleBatch.ACTION_LOGP not in batch:
            raise ValueError(
                "off-policy estimation needs behavior ACTION_LOGP in the "
                "logged batch (record rollouts with exploration forwards)"
            )
        for ep in batch.split_by_episode():
            obs = np.asarray(ep[SampleBatch.OBS])
            actions = np.asarray(ep[SampleBatch.ACTIONS])
            rewards = np.asarray(ep[SampleBatch.REWARDS], dtype=np.float64)
            behavior_logp = np.asarray(
                ep[SampleBatch.ACTION_LOGP], dtype=np.float64
            )
            target_logp = np.asarray(
                self.target_logp_fn(obs, actions), dtype=np.float64
            )
            delta = np.clip(
                target_logp - behavior_logp, -self.logp_clip, self.logp_clip
            )
            # Cumulative importance ratio rho_t = prod_{t'<=t} pi/beta.
            rho = np.exp(np.cumsum(delta))
            discounts = self.gamma ** np.arange(len(rewards))
            self._episodes.append(
                {
                    "rho": rho,
                    "disc_rewards": discounts * rewards,
                    "v_behavior": float(np.sum(discounts * rewards)),
                }
            )

    # -- estimates ----------------------------------------------------------

    def estimate(self) -> Dict[str, float]:
        raise NotImplementedError

    def _check(self) -> None:
        if not self._episodes:
            raise ValueError("no episodes processed")


class ImportanceSampling(OffPolicyEstimator):
    """Per-decision IS: V = E_ep[ sum_t gamma^t rho_t r_t ]."""

    def estimate(self) -> Dict[str, float]:
        self._check()
        v_target = [
            float(np.sum(ep["rho"] * ep["disc_rewards"]))
            for ep in self._episodes
        ]
        v_behavior = [ep["v_behavior"] for ep in self._episodes]
        return {
            "v_behavior": float(np.mean(v_behavior)),
            "v_target": float(np.mean(v_target)),
            "v_gain": float(np.mean(v_target))
            / max(abs(float(np.mean(v_behavior))), 1e-9),
            "v_target_std": float(np.std(v_target)),
            "num_episodes": len(self._episodes),
        }


class WeightedImportanceSampling(OffPolicyEstimator):
    """Per-decision WIS: rho_t is normalized by the mean rho_t across
    episodes still alive at step t (Precup 2000; the reference's
    weighted_importance_sampling.py)."""

    def estimate(self) -> Dict[str, float]:
        self._check()
        max_len = max(len(ep["rho"]) for ep in self._episodes)
        # Per-timestep population mean of rho over episodes that reach t.
        sums = np.zeros(max_len)
        counts = np.zeros(max_len)
        for ep in self._episodes:
            t = len(ep["rho"])
            sums[:t] += ep["rho"]
            counts[:t] += 1.0
        w_mean = sums / np.maximum(counts, 1.0)
        w_mean = np.where(w_mean <= 0.0, 1.0, w_mean)
        v_target = []
        for ep in self._episodes:
            t = len(ep["rho"])
            v_target.append(
                float(np.sum((ep["rho"] / w_mean[:t]) * ep["disc_rewards"]))
            )
        v_behavior = [ep["v_behavior"] for ep in self._episodes]
        return {
            "v_behavior": float(np.mean(v_behavior)),
            "v_target": float(np.mean(v_target)),
            "v_gain": float(np.mean(v_target))
            / max(abs(float(np.mean(v_behavior))), 1e-9),
            "v_target_std": float(np.std(v_target)),
            "num_episodes": len(self._episodes),
        }


def estimate_from_reader(
    estimator: OffPolicyEstimator, reader, num_batches: int = 10
) -> Dict[str, float]:
    """Feed `num_batches` from a JsonReader (or any .next() source) through
    the estimator and return its estimate."""
    for _ in range(num_batches):
        estimator.process(reader.next())
    return estimator.estimate()
