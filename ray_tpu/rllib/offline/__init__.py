"""Offline RL IO — write rollouts out, read experience back in.

Reference: rllib/offline/ (JsonWriter/JsonReader + dataset-based IO). Batches
persist as JSON-lines of column dicts (human-greppable, append-friendly);
readers shuffle across files and yield SampleBatches for off-policy or
imitation training. `config.output` on any algorithm tees sampled rollouts to
a writer; `BC` (algorithms/bc) trains purely from a reader with no env
interaction.
"""

from __future__ import annotations

import base64
import json
import os
import uuid
from typing import Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


def _encode_column(arr) -> dict:
    arr = np.asarray(arr)
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "data": base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode(),
    }


def _decode_column(spec: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(spec["data"]), dtype=np.dtype(spec["dtype"])
    ).reshape(spec["shape"])


class JsonWriter:
    """Appends SampleBatches to .jsonl files under a directory (one line per
    batch; reference: rllib/offline/json_writer.py)."""

    def __init__(self, path: str, max_file_size_mb: float = 64.0):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._max_bytes = int(max_file_size_mb * 1024 * 1024)
        self._file = None
        self._written = 0

    def _rotate(self) -> None:
        if self._file is not None:
            self._file.close()
        fname = os.path.join(self.path, f"batches-{uuid.uuid4().hex[:8]}.jsonl")
        self._file = open(fname, "a")
        self._written = 0

    def write(self, batch: SampleBatch) -> None:
        if self._file is None or self._written > self._max_bytes:
            self._rotate()
        record = {
            k: _encode_column(v)
            for k, v in batch.items()
            if k != SampleBatch.INFOS
        }
        line = json.dumps(record)
        self._file.write(line + "\n")
        self._file.flush()
        self._written += len(line)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Streams batches back, cycling over files forever (training loops
    decide how much to consume; reference: rllib/offline/json_reader.py).
    Never materializes the dataset: one line is decoded at a time, so
    multi-GB logs read in constant memory. `shuffle` permutes FILE order per
    epoch (lines stream in order within a file — draw train batches with
    sample_rows for row-level mixing)."""

    def __init__(self, path: str, shuffle: bool = True, seed: Optional[int] = None):
        self.path = path
        self._rng = np.random.default_rng(seed)
        self._shuffle = shuffle
        self._files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(".jsonl")
        )
        if not self._files:
            raise FileNotFoundError(f"No .jsonl batch files under {path!r}")
        if self._shuffle:
            self._rng.shuffle(self._files)
        self._file_idx = 0
        self._fh = None

    def next(self) -> SampleBatch:
        while True:
            if self._fh is None:
                self._fh = open(self._files[self._file_idx])
            line = self._fh.readline()
            if not line:
                self._fh.close()
                self._fh = None
                self._file_idx += 1
                if self._file_idx >= len(self._files):
                    self._file_idx = 0
                    if self._shuffle:
                        self._rng.shuffle(self._files)
                continue
            line = line.strip()
            if not line:
                continue
            record = json.loads(line)
            return SampleBatch(
                {k: _decode_column(v) for k, v in record.items()}
            )

    def iter_batches(self) -> Iterator[SampleBatch]:
        while True:
            yield self.next()

    def sample_rows(self, n: int) -> SampleBatch:
        """A batch of exactly n rows drawn across stored batches."""
        out: List[SampleBatch] = []
        count = 0
        while count < n:
            b = self.next()
            out.append(b)
            count += b.count
        merged = SampleBatch.concat_samples(out)
        if merged.count > n:
            start = int(self._rng.integers(0, merged.count - n + 1))
            merged = merged.slice(start, start + n)
        return merged


from ray_tpu.rllib.offline.estimators import (  # noqa: E402
    ImportanceSampling,
    OffPolicyEstimator,
    WeightedImportanceSampling,
    estimate_from_reader,
)

__all__ = [
    "ImportanceSampling",
    "JsonReader",
    "JsonWriter",
    "OffPolicyEstimator",
    "WeightedImportanceSampling",
    "estimate_from_reader",
]
