"""ray_tpu.rllib — RL at scale, TPU-native.

Re-design of the reference's RLlib **new stack only** (SURVEY.md §2.3, §7.7):
RLModule (flax) / Learner (jitted SGD over a device mesh) / LearnerGroup
(learner actors on TPU hosts) / EnvRunner actor pool on CPU nodes. The legacy
Policy/RolloutWorker stack (rllib/policy/, rllib/evaluation/rollout_worker.py)
is deliberately not reproduced — the reference was migrating off it.

Layering rule preserved from the reference: rllib uses only the public
task/actor/object API (ray_tpu.remote / actors / ObjectRefs) — no runtime
internals.
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "MultiAgentBatch",
    "SampleBatch",
]
